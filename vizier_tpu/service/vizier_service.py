"""VizierServicer: study/trial lifecycle + Pythia dispatch.

Parity with ``/root/reference/vizier/_src/service/vizier_service.py:64``
(init ``:73``, ``SuggestTrials`` ``:245``, ``CompleteTrial`` ``:568``,
``CheckTrialEarlyStoppingState`` ``:631``, ``ListOptimalTrials`` ``:861``,
``UpdateMetadata`` ``:931``), re-implemented against our own wire schema.
The multi-worker behavioral contract is preserved exactly:

- per-(owner/study/operation) locks; datastore does its own locking;
- ``SuggestTrials`` first returns the client's existing ACTIVE trials, then
  drains the REQUESTED pool, then dispatches to Pythia — so a crashed
  worker that re-requests gets its old trials back;
- suggestion operations are deduplicated per client (an unfinished op for
  the same client is returned as-is);
- Pythia failures are captured into the operation's ``error`` field;
- completed trials and completed studies are immutable;
- early-stopping ops are recycled after ``early_stop_recycle_period``.
"""

from __future__ import annotations

import collections
import datetime
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

_logger = logging.getLogger(__name__)

from vizier_tpu import pyvizier as vz
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.reliability import config as reliability_config_lib
from vizier_tpu.reliability import deadline as deadline_lib
from vizier_tpu.reliability import errors as errors_lib
from vizier_tpu.service import datastore as datastore_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_util
from vizier_tpu.service import ram_datastore
from vizier_tpu.service import resources
from vizier_tpu.service import sql_datastore
from vizier_tpu.service.protos import study_pb2, vizier_service_pb2


class VizierServicer:
    """The study service; callable in-process or wrapped by gRPC."""

    # Which fleet replica this servicer is (set by ReplicaManager /
    # replica_main); '' = standalone. Stamped onto request spans so a
    # fleet merge can split one process's span ring back into per-replica
    # dumps (observability.fleet).
    replica_id = ""

    def __init__(
        self,
        *,
        database_url: Optional[str] = None,
        datastore: Optional[datastore_lib.DataStore] = None,
        early_stop_recycle_period: datetime.timedelta = datetime.timedelta(seconds=60),
        reliability_config: Optional[reliability_config_lib.ReliabilityConfig] = None,
    ):
        # An injected datastore wins: the sharded tier hands each replica
        # its own snapshot+WAL-backed store (vizier_tpu.distributed), and a
        # ShardedDataStore partitions one servicer across shard stores.
        if datastore is not None:
            if database_url is not None:
                raise ValueError("Pass either datastore or database_url, not both.")
            self.datastore: datastore_lib.DataStore = datastore
        elif database_url is None:
            self.datastore = ram_datastore.NestedDictRAMDataStore()
        else:
            self.datastore = sql_datastore.SQLDataStore(database_url)
        self._early_stop_recycle_period = early_stop_recycle_period
        self._reliability = (
            reliability_config or reliability_config_lib.ReliabilityConfig.from_env()
        )
        self._study_locks: Dict[str, threading.Lock] = collections.defaultdict(
            threading.Lock
        )
        self._policy_factory = None  # set via set_policy_factory / pythia servicer
        self._pythia = None  # object with Suggest/EarlyStop proto methods
        # Ops created by THIS process; a persisted not-done op absent from
        # here was orphaned by a crash and must not wedge its client.
        self._inflight_ops: set = set()

    def set_pythia(self, pythia) -> None:
        """Connects a Pythia endpoint (in-process servicer or gRPC stub)."""
        self._pythia = pythia

    # -- observability (in-process Pythia only) ----------------------------

    def _serving_stats_sink(self):
        """The connected Pythia's ServingStats, or None (remote stub)."""
        runtime = getattr(self._pythia, "serving_runtime", None)
        return runtime.stats if runtime is not None else None

    def serving_stats(self) -> dict:
        """Delegates to the in-process Pythia servicer's counters."""
        snapshot = getattr(self._pythia, "serving_stats", None)
        return snapshot() if snapshot is not None else {}

    def prometheus_text(self) -> str:
        """Delegates to the in-process Pythia's metric dump ('' if remote)."""
        dump = getattr(self._pythia, "prometheus_text", None)
        return dump() if dump is not None else ""

    def trial_frontier(self, study_name: str) -> Tuple[List[int], List[int], int]:
        """``(completed_ids, active_ids, max_trial_id)`` for a study.

        The designer-visible frontier identity, read as bare id/state
        pairs (no proto copies): completed = SUCCEEDED|INFEASIBLE (what
        the policy feeds ``designer.update``), active = ACTIVE (the
        pending points batch designers condition on). The speculative
        pre-compute pipeline fingerprints this to decide whether a parked
        suggestion batch still matches reality.
        """
        completed: List[int] = []
        active: List[int] = []
        max_id = 0
        for trial_id, state in self.datastore.trial_states(study_name):
            trial_id = int(trial_id)
            max_id = max(max_id, trial_id)
            if state in (study_pb2.Trial.SUCCEEDED, study_pb2.Trial.INFEASIBLE):
                completed.append(trial_id)
            elif state == study_pb2.Trial.ACTIVE:
                active.append(trial_id)
        return completed, active, max_id

    def _notify_trial_event(self, study_name: str) -> None:
        """Tells the in-process Pythia the study's frontier moved, so it
        can invalidate + re-speculate the next suggestion batch. Called
        OUTSIDE the study lock (the engine enqueue takes its own queue
        lock; nesting it under a study lock would widen the serving lock
        graph for a trigger that needs no datastore state). Best-effort:
        a remote Pythia stub has no trigger surface and relies on the
        serve-time fingerprint check alone."""
        notify = getattr(self._pythia, "notify_trial_event", None)
        if notify is None:
            return
        try:
            notify(study_name)
        except Exception as e:  # completion must not fail on speculation
            _logger.warning("Speculative trigger failed for %s: %s", study_name, e)

    def record_client_retry(self, amount: int = 1) -> None:
        """Client-side retry accounting (no-op without in-process Pythia).

        Clients of the in-process servicer report their RPC/suggest retries
        here so they surface in ``serving_stats()`` next to the server-side
        fallback/breaker counters; a remote client's retries are only
        observable client-side.
        """
        stats = self._serving_stats_sink()
        if stats is not None:
            stats.increment("retries", amount)

    # -- studies -----------------------------------------------------------

    def CreateStudy(
        self, request: vizier_service_pb2.CreateStudyRequest, context=None
    ) -> study_pb2.Study:
        owner = resources.OwnerResource.from_name(request.parent)
        study = request.study
        if not study.name:
            study_id = study.display_name or f"study-{int(time.time() * 1e6)}"
            study.name = f"{owner.name}/studies/{study_id}"
        try:
            self.datastore.create_study(study)
        except datastore_lib.AlreadyExistsError:
            # create_or_load semantics: return the existing study.
            return self.datastore.load_study(study.name)
        return self.datastore.load_study(study.name)

    def GetStudy(
        self, request: vizier_service_pb2.GetStudyRequest, context=None
    ) -> study_pb2.Study:
        return self.datastore.load_study(request.name)

    def ListStudies(
        self, request: vizier_service_pb2.ListStudiesRequest, context=None
    ) -> vizier_service_pb2.ListStudiesResponse:
        return vizier_service_pb2.ListStudiesResponse(
            studies=self.datastore.list_studies(request.parent)
        )

    def DeleteStudy(
        self, request: vizier_service_pb2.DeleteStudyRequest, context=None
    ) -> vizier_service_pb2.Empty:
        self.datastore.delete_study(request.name)
        # Explicitly drop the study's serving state (cached designer, warm
        # ARD params, stopping policies): a reused study name must never
        # see its predecessor's designer. In-process Pythia only — a remote
        # Pythia stub has no invalidation RPC and relies on the cache TTL.
        invalidate = getattr(self._pythia, "invalidate_study", None)
        if invalidate is not None:
            try:
                invalidate(request.name)
            except Exception as e:  # deletion must not fail on cache cleanup
                _logger.warning("Serving-state invalidation failed: %s", e)
        return vizier_service_pb2.Empty()

    def SetStudyState(
        self, request: vizier_service_pb2.SetStudyStateRequest, context=None
    ) -> study_pb2.Study:
        study = self.datastore.load_study(request.name)
        study.state = request.state
        study.state_reason = request.reason
        self.datastore.update_study(study)
        return study

    # -- suggestions -------------------------------------------------------

    def SuggestTrials(
        self, request: vizier_service_pb2.SuggestTrialsRequest, context=None
    ) -> vizier_service_pb2.Operation:
        # The service hop's span: parented on the client's span when the
        # request carries a trace context, a fresh trace otherwise.
        tracer = tracing_lib.get_tracer()
        parent = tracing_lib.parse_context(request.trace_context)
        t0 = time.perf_counter()
        attrs = {"replica": self.replica_id} if self.replica_id else {}
        with tracer.span(
            "service.suggest_trials",
            parent=parent,
            study=request.parent,
            client_id=request.client_id or "default_client_id",
            deadline_budget_secs=float(request.deadline_secs),
            **attrs,
        ) as span:
            op = self._suggest_trials(request)
            span.set_attribute("operation", op.name)
            if op.error:
                span.set_attribute("error", op.error.splitlines()[0][:200])
            trace_id = getattr(span, "trace_id", None)
        elapsed = time.perf_counter() - t0
        recorder_lib.get_recorder().record(
            request.parent, "suggest", trace_id=trace_id,
            operation=op.name, replica=self.replica_id or None,
            duration_secs=round(elapsed, 6), error=bool(op.error),
        )
        runtime = getattr(self._pythia, "serving_runtime", None)
        if runtime is not None:
            tenant = None
            if getattr(runtime, "admission", None) is not None:
                # Per-tenant latency series (admission armed only, so the
                # seed metric series stay byte-identical with it off):
                # feeds the SLO engine's per-tenant p99 objective.
                from vizier_tpu.serving import admission as admission_lib

                tenant = admission_lib.tenant_of(request.parent)
            runtime.observe_suggest_latency(
                "service", elapsed, trace_id=trace_id, tenant=tenant
            )
        return op

    def _suggest_trials(
        self, request: vizier_service_pb2.SuggestTrialsRequest
    ) -> vizier_service_pb2.Operation:
        study_name = request.parent
        client_id = request.client_id or "default_client_id"

        # Ingress deadline check: a request whose wire budget is already
        # expired (negative ``deadline_secs`` — the client's remaining
        # budget at send time) must never reach Pythia: the caller has
        # given up, so a designer computation would complete work nobody
        # reads. Short-circuit with the typed error on a synthetic done
        # op — no op number is consumed, nothing is persisted.
        if self._reliability.deadlines_on and request.deadline_secs < 0:
            stats = self._serving_stats_sink()
            if stats is not None:
                stats.increment("deadline_exceeded")
            tracing_lib.add_current_event(
                "deadline.exceeded", at="service_ingress"
            )
            recorder_lib.get_recorder().record(
                study_name, "deadline_expired_at_ingress",
                budget_secs=float(request.deadline_secs),
            )
            op = vizier_service_pb2.Operation(
                name=(
                    f"{study_name}/clients/{client_id}/operations/expired"
                ),
                done=True,
            )
            op.error = errors_lib.format_op_error(
                errors_lib.DeadlineExceededError(
                    errors_lib.mark_transient(
                        "DEADLINE_EXCEEDED: request budget expired "
                        f"{-request.deadline_secs:.3f}s before dispatch; "
                        "designer computation skipped."
                    )
                )
            )
            return op
        with self._study_locks[study_name]:
            study = self.datastore.load_study(study_name)
            if study.state != study_pb2.Study.ACTIVE:
                raise ValueError(f"Study {study_name} is not ACTIVE.")

            # Op dedup: an unfinished op for this client is returned as-is —
            # unless it was orphaned by a server crash (persisted not-done
            # but not in flight here), in which case it is failed and retried.
            unfinished = self.datastore.list_suggestion_operations(
                study_name, client_id, done=False
            )
            for op in unfinished:
                if op.name in self._inflight_ops:
                    return op
                op.done = True
                op.error = "Orphaned by server restart; retry."
                self.datastore.update_suggestion_operation(op)

            op_number = self.datastore.max_suggestion_operation_number(
                study_name, client_id
            ) + 1
            sr = resources.StudyResource.from_name(study_name)
            op = vizier_service_pb2.Operation(
                name=resources.SuggestionOperationResource(
                    sr.owner_id, sr.study_id, client_id, op_number
                ).name
            )
            self.datastore.create_suggestion_operation(op)
            self._inflight_ops.add(op.name)

        # The Pythia dispatch runs OUTSIDE the study lock (see _suggest):
        # the lock protects datastore read-modify-write windows, not the
        # designer computation. Concurrent clients therefore reach Pythia
        # with the same trial frontier and coalesce onto ONE computation
        # (vizier_tpu.serving); a same-client retry meanwhile sees the
        # not-done op above and polls GetOperation, the reference's
        # long-running-operation contract.
        #
        # The client's deadline budget (request.deadline_secs, remaining
        # seconds) becomes a Deadline here and is decremented across every
        # hop below; transient failures are marked TRANSIENT: in op.error
        # so client retry logic can tell them from permanent errors.
        deadline = (
            deadline_lib.Deadline.from_budget(request.deadline_secs)
            if self._reliability.deadlines_on
            else deadline_lib.Deadline.none()
        )
        try:
            trials = self._suggest(
                study, study_name, client_id, request, deadline, op.name
            )
            op.response.trials.extend(trials)
        except Exception as e:  # captured into the long-running op
            op.error = errors_lib.format_op_error(e)
        finally:
            op.done = True
            self.datastore.update_suggestion_operation(op)
            self._inflight_ops.discard(op.name)
        return op

    def _claim_open_trials(
        self, study_name: str, client_id: str, count: int, *, reuse_active: bool = True
    ) -> Tuple[List[study_pb2.Trial], bool]:
        """Under the study lock: ACTIVE reuse, then REQUESTED-pool drain.

        Returns ``(trials, reused)``: ``reused`` means the client's
        existing ACTIVE trials were returned (no pool mutation).
        ``reuse_active=False`` skips that branch — the post-compute
        re-drain must not hand the client back the trials it claimed in
        phase 1.
        """
        # Only ACTIVE/REQUESTED rows matter here; the storage-level filter
        # keeps this scan O(open trials) instead of O(study history)
        # (measured: RANDOM_SEARCH suggest throughput fell 430→50/s over a
        # 5k-trial soak with the unfiltered read).
        open_trials = self.datastore.list_trials(
            study_name,
            states=(study_pb2.Trial.ACTIVE, study_pb2.Trial.REQUESTED),
        )

        # 1. Reuse this client's ACTIVE trials.
        if reuse_active:
            active_for_client = [
                t
                for t in open_trials
                if t.state == study_pb2.Trial.ACTIVE
                and t.assigned_worker == client_id
            ]
            if active_for_client:
                return active_for_client[:count], True

        # 2. Drain the REQUESTED pool.
        out: List[study_pb2.Trial] = []
        for t in open_trials:
            if len(out) >= count:
                break
            if t.state == study_pb2.Trial.REQUESTED:
                t.state = study_pb2.Trial.ACTIVE
                t.assigned_worker = client_id
                self.datastore.update_trial(t)
                out.append(t)
        return out, False

    def _suggest(
        self,
        study: study_pb2.Study,
        study_name: str,
        client_id: str,
        request: vizier_service_pb2.SuggestTrialsRequest,
        deadline: deadline_lib.Deadline = deadline_lib.Deadline.none(),
        operation_name: str = "",
    ) -> List[study_pb2.Trial]:
        count = request.suggestion_count or 1
        with self._study_locks[study_name]:
            out, reused = self._claim_open_trials(study_name, client_id, count)
            if reused or len(out) >= count:
                return out
            max_id = self.datastore.max_trial_id(study_name)

        # 3. Ask Pythia for the remainder — lock released, so concurrent
        # clients' identical requests can coalesce at the compute level.
        if self._pythia is None:
            raise RuntimeError("No Pythia endpoint connected to the Vizier service.")
        from vizier_tpu.service.protos import pythia_service_pb2

        deadline.check(f"Pythia dispatch for operation {operation_name!r}")
        preq = pythia_service_pb2.PythiaSuggestRequest(
            count=count - len(out),
            algorithm=study.study_spec.algorithm,
            study_name=study_name,
            deadline_secs=deadline.wire_budget(),
        )
        preq.study_descriptor.config.CopyFrom(study.study_spec)
        preq.study_descriptor.guid = study_name
        preq.study_descriptor.max_trial_id = max_id
        tracer = tracing_lib.get_tracer()
        with tracer.span(
            "service.pythia_dispatch",
            study=study_name,
            deadline_remaining_secs=(
                deadline.remaining() if deadline.is_set else 0.0
            ),
        ) as dispatch_span:
            # The dispatch span rides the wire so Pythia's spans parent
            # correctly even across the worker-thread / process hop.
            preq.trace_context = tracing_lib.format_context(
                dispatch_span.context()
            )
            presp = self._dispatch_pythia(preq, deadline, operation_name)
        if presp.error:
            if errors_lib.has_transient_marker(presp.error):
                raise errors_lib.TransientError(f"Pythia error: {presp.error}")
            raise RuntimeError(f"Pythia error: {presp.error}")

        sr = resources.StudyResource.from_name(study_name)
        with self._study_locks[study_name]:
            # Re-drain first: a coalesced peer that shared this computation
            # may have materialized extras as REQUESTED while we waited —
            # claiming those avoids creating duplicate trials for the same
            # suggested points.
            refill, _ = self._claim_open_trials(
                study_name, client_id, count - len(out), reuse_active=False
            )
            redrained = bool(refill)
            out.extend(refill)

            # Materialize suggestions as trials: the first `remaining`
            # become ACTIVE for this client; extras (policy over-produced)
            # stay REQUESTED. When the re-drain supplied trials, only the
            # shortfall is materialized — the shared computation's points
            # already exist as the peer's trials.
            remaining = count - len(out)
            to_create = (
                list(presp.suggestions)[:remaining]
                if redrained
                else list(presp.suggestions)
            )
            next_id = self.datastore.max_trial_id(study_name)
            for i, suggestion in enumerate(to_create):
                next_id += 1
                t = study_pb2.Trial()
                t.CopyFrom(suggestion)
                t.id = next_id
                t.name = sr.trial_resource(next_id).name
                t.creation_time_secs = time.time()
                if i < remaining:
                    t.state = study_pb2.Trial.ACTIVE
                    t.assigned_worker = client_id
                else:
                    t.state = study_pb2.Trial.REQUESTED
                self.datastore.create_trial(t)
                if i < remaining:
                    out.append(t)

            # Persist policy metadata deltas AFTER trial creation so deltas
            # addressed to the new suggestions' ids resolve; a bad delta must
            # not lose the suggestion batch.
            study_kvs, trial_kvs = [], []
            for delta in presp.metadata_deltas:
                for kv in delta.key_values:
                    if delta.trial_id == 0:
                        study_kvs.append(kv)
                    else:
                        trial_kvs.append((int(delta.trial_id), kv))
            if study_kvs or trial_kvs:
                try:
                    self.datastore.update_metadata(study_name, study_kvs, trial_kvs)
                except datastore_lib.NotFoundError as e:
                    _logger.warning("Dropping policy metadata delta: %s", e)
        return out

    def _dispatch_pythia(self, preq, deadline: deadline_lib.Deadline, operation_name: str):
        """Runs the Pythia Suggest, bounded by the remaining deadline.

        With no deadline the call is synchronous (the seed's shape). With
        one, the computation runs on a daemon thread reporting into a
        ``ResponseWaiter`` and the wait is capped at the remaining budget:
        a wedged designer can no longer hold the study's frontier past the
        client's deadline — the op completes with a typed
        ``TRANSIENT: DEADLINE_EXCEEDED:`` error while the abandoned
        computation finishes (and is discarded) in the background.
        """
        if not deadline.is_set:
            return self._pythia.Suggest(preq)
        waiter: pythia_util.ResponseWaiter = pythia_util.ResponseWaiter(
            operation_name=operation_name
        )
        # The worker thread starts with an empty contextvars context; carry
        # the dispatch span over so any spans opened on that thread (beyond
        # what the proto's trace_context already covers) parent correctly.
        tracer = tracing_lib.get_tracer()
        dispatch_ctx = tracer.current_context()

        def run():
            try:
                with tracer.use_context(dispatch_ctx):
                    waiter.Report(self._pythia.Suggest(preq))
            except BaseException as e:  # pragma: no cover - defensive
                try:
                    waiter.ReportError(e)
                except RuntimeError:
                    pass  # waiter already completed (should not happen)

        threading.Thread(
            target=run, daemon=True, name=f"pythia-suggest-{operation_name}"
        ).start()
        try:
            return waiter.WaitForResponse(timeout=max(0.0, deadline.remaining()))
        except TimeoutError as e:
            stats = self._serving_stats_sink()
            if stats is not None:
                stats.increment("deadline_exceeded")
            tracing_lib.add_current_event(
                "deadline.exceeded", at="pythia_wait", operation=operation_name
            )
            raise errors_lib.DeadlineExceededError(
                errors_lib.mark_transient(f"DEADLINE_EXCEEDED: {e}")
            ) from None

    def GetOperation(
        self, request: vizier_service_pb2.GetOperationRequest, context=None
    ) -> vizier_service_pb2.Operation:
        return self.datastore.get_suggestion_operation(request.name)

    # -- trials ------------------------------------------------------------

    def CreateTrial(
        self, request: vizier_service_pb2.CreateTrialRequest, context=None
    ) -> study_pb2.Trial:
        study_name = request.parent
        with self._study_locks[study_name]:
            sr = resources.StudyResource.from_name(study_name)
            trial = request.trial
            trial.id = self.datastore.max_trial_id(study_name) + 1
            trial.name = sr.trial_resource(trial.id).name
            if trial.state == study_pb2.Trial.STATE_UNSPECIFIED:
                trial.state = study_pb2.Trial.ACTIVE
            trial.creation_time_secs = time.time()
            self.datastore.create_trial(trial)
            return trial

    def GetTrial(
        self, request: vizier_service_pb2.GetTrialRequest, context=None
    ) -> study_pb2.Trial:
        return self.datastore.get_trial(request.name)

    def ListTrials(
        self, request: vizier_service_pb2.ListTrialsRequest, context=None
    ) -> vizier_service_pb2.ListTrialsResponse:
        return vizier_service_pb2.ListTrialsResponse(
            trials=self.datastore.list_trials(request.parent)
        )

    def AddTrialMeasurement(
        self, request: vizier_service_pb2.AddTrialMeasurementRequest, context=None
    ) -> study_pb2.Trial:
        study_name = resources.TrialResource.from_name(
            request.trial_name
        ).study_resource.name
        # Read-modify-write under the study lock: two workers racing here must
        # not both pass the completed check or drop each other's measurement.
        with self._study_locks[study_name]:
            trial = self.datastore.get_trial(request.trial_name)
            if trial.state in (study_pb2.Trial.SUCCEEDED, study_pb2.Trial.INFEASIBLE):
                raise ValueError(f"Trial {request.trial_name} is already completed.")
            trial.measurements.add().CopyFrom(request.measurement)
            self.datastore.update_trial(trial)
        self._notify_trial_event(study_name)
        return trial

    def CompleteTrial(
        self, request: vizier_service_pb2.CompleteTrialRequest, context=None
    ) -> study_pb2.Trial:
        study_name = resources.TrialResource.from_name(request.name).study_resource.name
        # The completion gets a span of its own: it is the trigger edge of
        # the speculative pre-compute pipeline, and the precompute span
        # links back here — "this completion set that compute in motion".
        tracer = tracing_lib.get_tracer()
        attrs = {"replica": self.replica_id} if self.replica_id else {}
        with tracer.span(
            "service.complete_trial", study=study_name, trial=request.name,
            **attrs,
        ) as span:
            trial = self._complete_trial(request, study_name)
            self._notify_trial_event(study_name)
            trace_id = getattr(span, "trace_id", None)
        recorder_lib.get_recorder().record(
            study_name, "complete", trace_id=trace_id, trial=request.name,
            replica=self.replica_id or None,
            state=study_pb2.Trial.State.Name(trial.state),
        )
        return trial

    def _complete_trial(
        self, request: vizier_service_pb2.CompleteTrialRequest, study_name: str
    ) -> study_pb2.Trial:
        with self._study_locks[study_name]:
            trial = self.datastore.get_trial(request.name)
            study = self.datastore.load_study(study_name)
            if study.state == study_pb2.Study.COMPLETED:
                raise ValueError(
                    f"Study {study_name} is completed; trials are immutable."
                )
            if trial.state in (study_pb2.Trial.SUCCEEDED, study_pb2.Trial.INFEASIBLE):
                raise ValueError(f"Trial {request.name} is already completed.")

            if request.HasField("final_measurement"):
                trial.final_measurement.CopyFrom(request.final_measurement)
                trial.state = study_pb2.Trial.SUCCEEDED
            elif trial.measurements:
                trial.final_measurement.CopyFrom(trial.measurements[-1])
                trial.state = study_pb2.Trial.SUCCEEDED
            else:
                trial.state = study_pb2.Trial.INFEASIBLE
                trial.infeasibility_reason = (
                    request.infeasible_reason or "Completed without any measurement."
                )
            if request.trial_infeasible:
                trial.state = study_pb2.Trial.INFEASIBLE
                trial.infeasibility_reason = request.infeasible_reason or "infeasible"
            trial.completion_time_secs = time.time()
            self.datastore.update_trial(trial)
            return trial

    def DeleteTrial(
        self, request: vizier_service_pb2.DeleteTrialRequest, context=None
    ) -> vizier_service_pb2.Empty:
        self.datastore.delete_trial(request.name)
        return vizier_service_pb2.Empty()

    def StopTrial(
        self, request: vizier_service_pb2.StopTrialRequest, context=None
    ) -> study_pb2.Trial:
        study_name = resources.TrialResource.from_name(request.name).study_resource.name
        with self._study_locks[study_name]:
            trial = self.datastore.get_trial(request.name)
            if trial.state in (study_pb2.Trial.ACTIVE, study_pb2.Trial.REQUESTED):
                trial.state = study_pb2.Trial.STOPPING
                self.datastore.update_trial(trial)
            return trial

    # -- early stopping ----------------------------------------------------

    def CheckTrialEarlyStoppingState(
        self,
        request: vizier_service_pb2.CheckTrialEarlyStoppingStateRequest,
        context=None,
    ) -> vizier_service_pb2.CheckTrialEarlyStoppingStateResponse:
        tr = resources.TrialResource.from_name(request.trial_name)
        study_name = tr.study_resource.name
        with self._study_locks[study_name]:
            op_resource = resources.EarlyStoppingOperationResource(
                tr.owner_id, tr.study_id, tr.trial_id
            )
            now = time.time()
            period = self._early_stop_recycle_period.total_seconds()
            try:
                op = self.datastore.get_early_stopping_operation(op_resource.name)
                if op.status == vizier_service_pb2.EarlyStoppingOperation.DONE:
                    expired = now - op.completion_time_secs > period
                else:
                    # A stale ACTIVE op (Pythia crashed mid-computation) must
                    # also be recycled, or should_stop pins to False forever.
                    expired = now - op.creation_time_secs > period
                if not expired:
                    return vizier_service_pb2.CheckTrialEarlyStoppingStateResponse(
                        should_stop=op.should_stop
                    )
            except datastore_lib.NotFoundError:
                pass

            op = vizier_service_pb2.EarlyStoppingOperation(
                name=op_resource.name,
                status=vizier_service_pb2.EarlyStoppingOperation.ACTIVE,
                creation_time_secs=now,
            )
            self.datastore.create_early_stopping_operation(op)

            study = self.datastore.load_study(study_name)
            if not study.study_spec.HasField("early_stopping"):
                # Without a stopping config, nothing ever stops early.
                op.status = vizier_service_pb2.EarlyStoppingOperation.DONE
                op.should_stop = False
                op.completion_time_secs = time.time()
                self.datastore.update_early_stopping_operation(op)
                return vizier_service_pb2.CheckTrialEarlyStoppingStateResponse(
                    should_stop=False
                )
            if self._pythia is None:
                raise RuntimeError("No Pythia endpoint connected.")
            max_trial_id = self.datastore.max_trial_id(study_name)

        # The Pythia dispatch runs OUTSIDE the study lock, like the suggest
        # path: the lock protects datastore read-modify-write windows, not
        # the stopping-policy computation — holding it across a potentially
        # slow policy (or remote RPC) would stall every suggest/complete for
        # the study. A concurrent check racing this window sees the ACTIVE
        # op above and returns its (not-yet-stopping) answer instead of
        # blocking; it re-asks after the recycle period, the same contract
        # as a crashed-mid-computation op. Enforced by the lock_order
        # static-analysis pass ("no RPC under a study lock").
        from vizier_tpu.service.protos import pythia_service_pb2

        preq = pythia_service_pb2.PythiaEarlyStopRequest(
            trial_ids=[tr.trial_id],
            algorithm=study.study_spec.algorithm,
            study_name=study_name,
        )
        preq.study_descriptor.config.CopyFrom(study.study_spec)
        preq.study_descriptor.guid = study_name
        preq.study_descriptor.max_trial_id = max_trial_id
        presp = self._pythia.EarlyStop(preq)
        if presp.error:
            raise RuntimeError(f"Pythia error: {presp.error}")

        # Fan decisions out into per-trial ops (batch-aware policies may
        # return decisions for other trials too) — back under the lock for
        # the datastore writes.
        should_stop = False
        with self._study_locks[study_name]:
            for decision in presp.decisions:
                d_resource = resources.EarlyStoppingOperationResource(
                    tr.owner_id, tr.study_id, int(decision.id)
                )
                d_op = vizier_service_pb2.EarlyStoppingOperation(
                    name=d_resource.name,
                    status=vizier_service_pb2.EarlyStoppingOperation.DONE,
                    should_stop=decision.should_stop,
                    creation_time_secs=now,
                    completion_time_secs=time.time(),
                )
                self.datastore.create_early_stopping_operation(d_op)
                if int(decision.id) == tr.trial_id:
                    should_stop = decision.should_stop
        return vizier_service_pb2.CheckTrialEarlyStoppingStateResponse(
            should_stop=should_stop
        )

    # -- optimal trials ----------------------------------------------------

    def ListOptimalTrials(
        self, request: vizier_service_pb2.ListOptimalTrialsRequest, context=None
    ) -> vizier_service_pb2.ListOptimalTrialsResponse:
        study = self.datastore.load_study(request.parent)
        trials = [
            t
            for t in self.datastore.list_trials(
                request.parent, states=(study_pb2.Trial.SUCCEEDED,)
            )
            if t.HasField("final_measurement")
        ]
        response = vizier_service_pb2.ListOptimalTrialsResponse()
        if not trials:
            return response

        metric_specs = list(study.study_spec.metrics)
        objective_specs = [m for m in metric_specs if not m.HasField("safety_config")]
        if not objective_specs:
            return response

        # Matrix of objective values, sign-flipped so bigger is better.
        values = np.full((len(trials), len(objective_specs)), -np.inf)
        for i, t in enumerate(trials):
            by_name = {m.name: m.value for m in t.final_measurement.metrics}
            for j, spec in enumerate(objective_specs):
                if spec.name in by_name:
                    v = by_name[spec.name]
                    values[i, j] = -v if spec.goal == study_pb2.MetricSpec.MINIMIZE else v

        if values.shape[1] == 1:
            best = np.nanargmax(values[:, 0])
            response.optimal_trials.add().CopyFrom(trials[int(best)])
            return response

        # Pareto frontier via a pairwise domination matrix.
        dominated = np.zeros(len(trials), dtype=bool)
        for i in range(len(trials)):
            if dominated[i]:
                continue
            geq = np.all(values >= values[i], axis=1)
            gt = np.any(values > values[i], axis=1)
            if np.any(geq & gt):
                dominated[i] = True
        for i, t in enumerate(trials):
            if not dominated[i]:
                response.optimal_trials.add().CopyFrom(t)
        return response

    # -- metadata ----------------------------------------------------------

    def UpdateMetadata(
        self, request: vizier_service_pb2.UpdateMetadataRequest, context=None
    ) -> vizier_service_pb2.UpdateMetadataResponse:
        study_kvs, trial_kvs = [], []
        for delta in request.deltas:
            if delta.trial_id == 0:
                study_kvs.append(delta.key_value)
            else:
                trial_kvs.append((int(delta.trial_id), delta.key_value))
        try:
            self.datastore.update_metadata(request.name, study_kvs, trial_kvs)
        except datastore_lib.NotFoundError as e:
            return vizier_service_pb2.UpdateMetadataResponse(error_details=str(e))
        return vizier_service_pb2.UpdateMetadataResponse()
