"""In-RAM datastore: nested dicts, single lock, pass-by-value.

Parity with ``/root/reference/vizier/_src/service/ram_datastore.py:83``.
Protos are copied on the way in and out so callers can never mutate stored
state behind the lock.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, Iterable, List, Optional

from vizier_tpu.service import datastore
from vizier_tpu.service import resources
from vizier_tpu.service.protos import key_value_pb2, study_pb2, vizier_service_pb2


def _copy(proto):
    out = type(proto)()
    out.CopyFrom(proto)
    return out


# Trial states the suggest hot path scans for. The open/undone indexes
# below exist because even a filter-before-copy listing still iterates a
# study's whole history per call — measured as the residual O(n) after the
# copy cost was removed (suggest 0.4 -> 2.9 ms/round from 0 to 5k trials).
_OPEN_TRIAL_STATES = frozenset(
    (study_pb2.Trial.ACTIVE, study_pb2.Trial.REQUESTED)
)


class _StudyNode:
    def __init__(self, study: study_pb2.Study):
        self.study = study
        self.trials: Dict[int, study_pb2.Trial] = {}
        # ids of trials currently in an open (ACTIVE/REQUESTED) state —
        # kept in sync by every trial write under the datastore lock.
        self.open_trial_ids: set = set()
        # client_id -> {operation_number -> Operation}
        self.suggestion_ops: Dict[str, Dict[int, vizier_service_pb2.Operation]] = (
            collections.defaultdict(dict)
        )
        # client_id -> op numbers with done == False, same sync contract.
        self.undone_op_numbers: Dict[str, set] = collections.defaultdict(set)
        # Tracked maxima (the per-suggest id-allocation reads): updated on
        # create, recomputed only when the current max is deleted.
        self.max_trial: int = 0
        self.max_op_number: Dict[str, int] = collections.defaultdict(int)
        # trial_id -> EarlyStoppingOperation
        self.early_stopping_ops: Dict[str, vizier_service_pb2.EarlyStoppingOperation] = {}


class NestedDictRAMDataStore(datastore.DataStore):
    def __init__(self):
        self._lock = threading.Lock()
        # owner_id -> study_id -> _StudyNode
        self._owners: Dict[str, Dict[str, _StudyNode]] = collections.defaultdict(dict)

    # -- internal helpers (caller holds the lock) -------------------------

    def _node(self, study_name: str) -> _StudyNode:
        r = resources.StudyResource.from_name(study_name)
        try:
            return self._owners[r.owner_id][r.study_id]
        except KeyError:
            raise datastore.NotFoundError(f"No such study: {study_name}")

    # -- studies -----------------------------------------------------------

    def create_study(self, study: study_pb2.Study) -> str:
        r = resources.StudyResource.from_name(study.name)
        with self._lock:
            if r.study_id in self._owners[r.owner_id]:
                raise datastore.AlreadyExistsError(f"Study exists: {study.name}")
            self._owners[r.owner_id][r.study_id] = _StudyNode(_copy(study))
        return study.name

    def load_study(self, study_name: str) -> study_pb2.Study:
        with self._lock:
            return _copy(self._node(study_name).study)

    def update_study(self, study: study_pb2.Study) -> str:
        with self._lock:
            node = self._node(study.name)
            node.study = _copy(study)
        return study.name

    def delete_study(self, study_name: str) -> None:
        r = resources.StudyResource.from_name(study_name)
        with self._lock:
            if r.study_id not in self._owners.get(r.owner_id, {}):
                raise datastore.NotFoundError(f"No such study: {study_name}")
            del self._owners[r.owner_id][r.study_id]

    def list_studies(self, owner_name: str) -> List[study_pb2.Study]:
        r = resources.OwnerResource.from_name(owner_name)
        with self._lock:
            return [_copy(n.study) for n in self._owners.get(r.owner_id, {}).values()]

    # -- trials ------------------------------------------------------------

    def create_trial(self, trial: study_pb2.Trial) -> str:
        r = resources.TrialResource.from_name(trial.name)
        with self._lock:
            node = self._node(r.study_resource.name)
            if r.trial_id in node.trials:
                raise datastore.AlreadyExistsError(f"Trial exists: {trial.name}")
            node.trials[r.trial_id] = _copy(trial)
            if trial.state in _OPEN_TRIAL_STATES:
                node.open_trial_ids.add(r.trial_id)
            node.max_trial = max(node.max_trial, r.trial_id)
        return trial.name

    def get_trial(self, trial_name: str) -> study_pb2.Trial:
        r = resources.TrialResource.from_name(trial_name)
        with self._lock:
            node = self._node(r.study_resource.name)
            if r.trial_id not in node.trials:
                raise datastore.NotFoundError(f"No such trial: {trial_name}")
            return _copy(node.trials[r.trial_id])

    def update_trial(self, trial: study_pb2.Trial) -> str:
        r = resources.TrialResource.from_name(trial.name)
        with self._lock:
            node = self._node(r.study_resource.name)
            if r.trial_id not in node.trials:
                raise datastore.NotFoundError(f"No such trial: {trial.name}")
            node.trials[r.trial_id] = _copy(trial)
            if trial.state in _OPEN_TRIAL_STATES:
                node.open_trial_ids.add(r.trial_id)
            else:
                node.open_trial_ids.discard(r.trial_id)
        return trial.name

    def delete_trial(self, trial_name: str) -> None:
        r = resources.TrialResource.from_name(trial_name)
        with self._lock:
            node = self._node(r.study_resource.name)
            if r.trial_id not in node.trials:
                raise datastore.NotFoundError(f"No such trial: {trial_name}")
            del node.trials[r.trial_id]
            node.open_trial_ids.discard(r.trial_id)
            if r.trial_id == node.max_trial:
                node.max_trial = max(node.trials.keys(), default=0)

    def trial_states(self, study_name: str) -> List[tuple]:
        """Copy-free ``(id, state)`` scan — the speculative fingerprint
        read stays O(n) integer pairs even when trials carry long
        measurement histories."""
        with self._lock:
            node = self._node(study_name)
            return [(tid, t.state) for tid, t in sorted(node.trials.items())]

    def list_trials(
        self, study_name: str, *, states: Optional[tuple] = None
    ) -> List[study_pb2.Trial]:
        with self._lock:
            node = self._node(study_name)
            if states is not None and _OPEN_TRIAL_STATES.issuperset(states):
                # Hot path (suggest): walk only the open index — O(open),
                # not O(history).
                return [
                    _copy(node.trials[tid])
                    for tid in sorted(node.open_trial_ids)
                    if node.trials[tid].state in states
                ]
            # General listings filter before the copy (completed history
            # dominates a long study).
            return [
                _copy(t)
                for _, t in sorted(node.trials.items())
                if states is None or t.state in states
            ]

    def max_trial_id(self, study_name: str) -> int:
        with self._lock:
            return self._node(study_name).max_trial

    # -- suggestion operations --------------------------------------------

    def create_suggestion_operation(
        self, operation: vizier_service_pb2.Operation
    ) -> str:
        r = resources.SuggestionOperationResource.from_name(operation.name)
        with self._lock:
            node = self._node(
                resources.StudyResource(r.owner_id, r.study_id).name
            )
            ops = node.suggestion_ops[r.client_id]
            if r.operation_number in ops:
                raise datastore.AlreadyExistsError(f"Operation exists: {operation.name}")
            ops[r.operation_number] = _copy(operation)
            if not operation.done:
                node.undone_op_numbers[r.client_id].add(r.operation_number)
            node.max_op_number[r.client_id] = max(
                node.max_op_number[r.client_id], r.operation_number
            )
        return operation.name

    def get_suggestion_operation(
        self, operation_name: str
    ) -> vizier_service_pb2.Operation:
        r = resources.SuggestionOperationResource.from_name(operation_name)
        with self._lock:
            node = self._node(resources.StudyResource(r.owner_id, r.study_id).name)
            ops = node.suggestion_ops.get(r.client_id, {})
            if r.operation_number not in ops:
                raise datastore.NotFoundError(f"No such operation: {operation_name}")
            return _copy(ops[r.operation_number])

    def update_suggestion_operation(
        self, operation: vizier_service_pb2.Operation
    ) -> str:
        r = resources.SuggestionOperationResource.from_name(operation.name)
        with self._lock:
            node = self._node(resources.StudyResource(r.owner_id, r.study_id).name)
            ops = node.suggestion_ops.get(r.client_id, {})
            if r.operation_number not in ops:
                raise datastore.NotFoundError(f"No such operation: {operation.name}")
            ops[r.operation_number] = _copy(operation)
            if operation.done:
                node.undone_op_numbers[r.client_id].discard(r.operation_number)
            else:
                node.undone_op_numbers[r.client_id].add(r.operation_number)
        return operation.name

    def list_suggestion_operations(
        self,
        study_name: str,
        client_id: str,
        filter_fn: Optional[Callable[[vizier_service_pb2.Operation], bool]] = None,
        *,
        done: Optional[bool] = None,
    ) -> List[vizier_service_pb2.Operation]:
        with self._lock:
            node = self._node(study_name)
            client_ops = node.suggestion_ops.get(client_id, {})
            if done is False:
                # Hot path (suggest dedup): walk only the undone index —
                # O(undone), not O(session history).
                candidates = [
                    client_ops[num]
                    for num in sorted(node.undone_op_numbers.get(client_id, ()))
                ]
            else:
                candidates = [op for _, op in sorted(client_ops.items())]
            # Filter BEFORE copying: op protos embed their suggested trials,
            # so copy-then-filter makes every SuggestTrials dedup check
            # deep-copy the study's entire operation history (O(n) copies
            # per suggest, O(n^2) for a session — measured 2.3x throughput
            # loss at 200 trials). filter_fn runs on the live proto under
            # the NON-REENTRANT datastore lock: it must not mutate its
            # argument and must not call back into this datastore (all
            # in-tree callers are pure predicates like `not op.done`).
            ops = [
                _copy(op)
                for op in candidates
                if (done is None or op.done == done)
                and (filter_fn is None or filter_fn(op))
            ]
        return ops

    def max_suggestion_operation_number(self, study_name: str, client_id: str) -> int:
        with self._lock:
            node = self._node(study_name)
            return node.max_op_number.get(client_id, 0)

    # -- early stopping operations ----------------------------------------

    def create_early_stopping_operation(
        self, operation: vizier_service_pb2.EarlyStoppingOperation
    ) -> str:
        r = resources.EarlyStoppingOperationResource.from_name(operation.name)
        with self._lock:
            node = self._node(resources.StudyResource(r.owner_id, r.study_id).name)
            node.early_stopping_ops[operation.name] = _copy(operation)
        return operation.name

    def get_early_stopping_operation(
        self, operation_name: str
    ) -> vizier_service_pb2.EarlyStoppingOperation:
        r = resources.EarlyStoppingOperationResource.from_name(operation_name)
        with self._lock:
            node = self._node(resources.StudyResource(r.owner_id, r.study_id).name)
            if operation_name not in node.early_stopping_ops:
                raise datastore.NotFoundError(f"No such operation: {operation_name}")
            return _copy(node.early_stopping_ops[operation_name])

    def update_early_stopping_operation(
        self, operation: vizier_service_pb2.EarlyStoppingOperation
    ) -> str:
        r = resources.EarlyStoppingOperationResource.from_name(operation.name)
        with self._lock:
            node = self._node(resources.StudyResource(r.owner_id, r.study_id).name)
            if operation.name not in node.early_stopping_ops:
                raise datastore.NotFoundError(f"No such operation: {operation.name}")
            node.early_stopping_ops[operation.name] = _copy(operation)
        return operation.name

    # -- snapshot export ---------------------------------------------------

    def export_protos(self):
        """Copies of every stored proto: (studies, trials, ops, es_ops).

        One consistent cut under the lock, in deterministic (sorted) order —
        the snapshot/replication layers (``vizier_tpu.distributed.wal``)
        serialize these into compacted WAL records. Suggestion ops are
        ordered (client_id, op_number) within a study; trials by id.
        """
        studies, trials, ops, es_ops = [], [], [], []
        with self._lock:
            for owner_id in sorted(self._owners):
                for study_id in sorted(self._owners[owner_id]):
                    node = self._owners[owner_id][study_id]
                    studies.append(_copy(node.study))
                    trials.extend(
                        _copy(t) for _, t in sorted(node.trials.items())
                    )
                    for client_id in sorted(node.suggestion_ops):
                        ops.extend(
                            _copy(op)
                            for _, op in sorted(
                                node.suggestion_ops[client_id].items()
                            )
                        )
                    es_ops.extend(
                        _copy(op)
                        for _, op in sorted(node.early_stopping_ops.items())
                    )
        return studies, trials, ops, es_ops

    # -- metadata ----------------------------------------------------------

    def update_metadata(
        self,
        study_name: str,
        study_metadata: Iterable[key_value_pb2.KeyValue],
        trial_metadata: Iterable,
    ) -> None:
        with self._lock:
            node = self._node(study_name)
            _merge_key_values(node.study.study_spec.metadata, study_metadata)
            r = resources.StudyResource.from_name(study_name)
            for trial_id, kv in trial_metadata:
                if trial_id not in node.trials:
                    raise datastore.NotFoundError(
                        f"No such trial {trial_id} in {study_name}"
                    )
                _merge_key_values(node.trials[trial_id].metadata, [kv])


def _merge_key_values(existing_field, new_kvs) -> None:
    """Merges KeyValues into a repeated field ((ns, key) unique)."""
    for kv in new_kvs:
        for old in existing_field:
            if old.ns == kv.ns and old.key == kv.key:
                old.CopyFrom(kv)
                break
        else:
            existing_field.add().CopyFrom(kv)
