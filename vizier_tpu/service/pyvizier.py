"""Service-side pyvizier facade.

Parity with the reference's ``vizier/service/pyvizier`` namespace (the
service flavor of the shared data model — in this build they are unified,
so this module simply re-exports the canonical facade).
"""

from vizier_tpu.pyvizier import *  # noqa: F401,F403
from vizier_tpu.pyvizier import __all__  # noqa: F401
