"""Gradient-based acquisition maximization (continuous-only).

Parity with
``/root/reference/vizier/_src/algorithms/optimizers/lbfgsb_optimizer.py:230``:
maximizes a differentiable acquisition over [0, 1]^D via multi-restart
L-BFGS — bounds handled by a sigmoid reparameterization (same trick as the
ARD train), so the whole thing is one jitted program with vmapped restarts.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from vizier_tpu.models import kernels
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.optimizers import vectorized as vectorized_lib

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class LBFGSBOptimizer:
    """Continuous acquisition maximizer under the vectorized-result API."""

    num_restarts: int = 16
    maxiter: int = 50

    def __call__(
        self,
        score_fn: vectorized_lib.ScoreFn,
        rng: Array,
        *,
        num_continuous: int,
        count: int = 1,
    ) -> vectorized_lib.VectorizedOptimizerResult:
        def unconstrained_loss(z: Array) -> Array:
            x = jax.nn.sigmoid(z)[None, :]  # (0,1)^D
            feats = kernels.MixedFeatures(
                x, jnp.zeros((1, 0), jnp.int32)
            )
            return -score_fn(feats)[0]

        def run_one(key: Array) -> Tuple[Array, Array]:
            z0 = jax.random.normal(key, (num_continuous,), dtype=jnp.float32) * 2.0
            # ftol disabled: acquisition values are <<1, so a relative
            # ftol would act as a loose absolute threshold and stop the
            # maximization steps early; this path is cheap (tiny dims).
            z, loss = lbfgs_lib.lbfgs_minimize(
                unconstrained_loss, z0, maxiter=self.maxiter, ftol=0.0
            )
            return jax.nn.sigmoid(z), -loss

        keys = jax.random.split(rng, self.num_restarts)
        xs, scores = jax.vmap(run_one)(keys)
        top_scores, idx = jax.lax.top_k(scores, count)
        return vectorized_lib.VectorizedOptimizerResult(
            kernels.MixedFeatures(
                xs[idx], jnp.zeros((count, 0), jnp.int32)
            ),
            top_scores,
        )


@dataclasses.dataclass
class DesignerAsOptimizer:
    """Uses any Designer as a (gradient-free) acquisition optimizer.

    Parity with ``optimizers/designer_optimizer.py:93``: the acquisition is
    treated as the objective of a mini-study driven by the designer.
    """

    designer_factory: Callable  # problem -> Designer
    num_rounds: int = 20
    batch_size: int = 10

    def optimize(
        self,
        score_fn,  # list[TrialSuggestion] -> list[float]
        problem,
        *,
        count: int = 1,
    ):
        from vizier_tpu.algorithms import core as core_lib
        from vizier_tpu.pyvizier import base_study_config
        from vizier_tpu.pyvizier import trial as trial_

        # The designer optimizes a synthetic always-MAXIMIZE acquisition
        # metric over the caller's search space — the caller's own metric
        # goals must not flip the acquisition's sign.
        metric_name = "acquisition"
        inner_problem = base_study_config.ProblemStatement(
            search_space=problem.search_space,
            metric_information=base_study_config.MetricsConfig(
                [
                    base_study_config.MetricInformation(
                        name=metric_name,
                        goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE,
                    )
                ]
            ),
        )
        designer = self.designer_factory(inner_problem)
        del problem  # everything below uses inner_problem's metric
        scored = []
        next_id = 1
        for _ in range(self.num_rounds):
            suggestions = designer.suggest(self.batch_size)
            if not suggestions:
                break
            values = score_fn(suggestions)
            completed = []
            for s, v in zip(suggestions, values):
                t = s.to_trial(next_id)
                next_id += 1
                t.complete(
                    trial_.Measurement(metrics={metric_name: float(v)})
                )
                completed.append(t)
                scored.append((float(v), s))
            designer.update(core_lib.CompletedTrials(completed), core_lib.ActiveTrials())
        scored.sort(key=lambda pair: -pair[0])
        return [s for _, s in scored[:count]]
