"""Gradient-based acquisition maximization (continuous-only).

Parity with
``/root/reference/vizier/_src/algorithms/optimizers/lbfgsb_optimizer.py:230``:
maximizes a differentiable acquisition over [0, 1]^D via multi-restart
L-BFGS — bounds handled by a sigmoid reparameterization (same trick as the
ARD train), so the whole thing is one jitted program with vmapped restarts.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from vizier_tpu.models import kernels
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.optimizers import vectorized as vectorized_lib

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class LBFGSBOptimizer:
    """Continuous acquisition maximizer under the vectorized-result API."""

    num_restarts: int = 16
    maxiter: int = 50

    def __call__(
        self,
        score_fn: vectorized_lib.ScoreFn,
        rng: Array,
        *,
        num_continuous: int,
        count: int = 1,
    ) -> vectorized_lib.VectorizedOptimizerResult:
        def unconstrained_loss(z: Array) -> Array:
            x = jax.nn.sigmoid(z)[None, :]  # (0,1)^D
            feats = kernels.MixedFeatures(
                x, jnp.zeros((1, 0), jnp.int32)
            )
            return -score_fn(feats)[0]

        def run_one(key: Array) -> Tuple[Array, Array]:
            z0 = jax.random.normal(key, (num_continuous,), dtype=jnp.float32) * 2.0
            # ftol disabled: acquisition values are <<1, so a relative
            # ftol would act as a loose absolute threshold and stop the
            # maximization steps early; this path is cheap (tiny dims).
            z, loss = lbfgs_lib.lbfgs_minimize(
                unconstrained_loss, z0, maxiter=self.maxiter, ftol=0.0
            )
            return jax.nn.sigmoid(z), -loss

        keys = jax.random.split(rng, self.num_restarts)
        xs, scores = jax.vmap(run_one)(keys)
        top_scores, idx = jax.lax.top_k(scores, count)
        return vectorized_lib.VectorizedOptimizerResult(
            kernels.MixedFeatures(
                xs[idx], jnp.zeros((count, 0), jnp.int32)
            ),
            top_scores,
        )


@dataclasses.dataclass
class DesignerAsOptimizer:
    """Uses any Designer as a (gradient-free) acquisition optimizer.

    Parity with ``optimizers/designer_optimizer.py:93``: the acquisition is
    treated as the objective of a mini-study driven by the designer.
    """

    designer_factory: Callable  # problem -> Designer
    num_rounds: int = 20
    batch_size: int = 10

    def optimize(
        self,
        score_fn,  # list[TrialSuggestion] -> list[float] | {metric: [N] or [N,1]}
        problem,
        *,
        count: int = 1,
        score_fn_returns_dict: bool | None = None,
    ):
        """Runs a mini-study of the score function driven by the designer.

        ``score_fn`` may return a plain sequence of floats (scored against a
        synthetic MAXIMIZE "acquisition" metric, the common single-
        acquisition path) or — matching the reference's
        ``BatchTrialScoreFunction`` (``optimizers/base.py:34``) — a mapping
        of metric name to an [N] / [N, 1] array, in which case the caller's
        own metric goals rank the results (Pareto front for multi-metric).
        Pass ``score_fn_returns_dict`` to skip the classification probe.
        """
        import numpy as np

        from vizier_tpu.algorithms import core as core_lib
        from vizier_tpu.designers import random as random_lib
        from vizier_tpu.pyvizier import base_study_config
        from vizier_tpu.pyvizier import multimetric
        from vizier_tpu.pyvizier import trial as trial_

        probe_scored = None
        if score_fn_returns_dict is not None:
            dict_scores = score_fn_returns_dict
        else:
            # Classify from a real single-suggestion batch: an empty-batch
            # probe misclassifies list-style fns that can't handle []. The
            # evaluation is kept as a ranked candidate so it isn't wasted
            # (auto-classification costs this one probe evaluation; callers
            # with expensive/stateful score functions can pass
            # score_fn_returns_dict to skip it).
            try:
                probe = random_lib.RandomDesigner(
                    problem.search_space, seed=0
                ).suggest(1)
                values = score_fn(probe)
                dict_scores = isinstance(values, dict)
                if dict_scores:
                    probe_metrics = {
                        k: float(np.asarray(v[0]).reshape(()))
                        for k, v in values.items()
                    }
                else:
                    probe_metrics = {"acquisition": float(values[0])}
                probe_scored = (probe_metrics, probe[0])
            except (
                TypeError,
                ValueError,
                IndexError,
                KeyError,
                AssertionError,
                RuntimeError,  # includes jaxlib XlaRuntimeError
            ) as e:
                # Shape/arity-style failures mean "score_fn can't take the
                # 1-row probe" (jit-specialized callables raise TypeError/
                # ValueError/XlaRuntimeError; hand-guarded ones assert):
                # fall back to the problem-shape heuristic, loudly. Anything
                # else (a genuine score_fn bug) propagates to the caller
                # instead of being silently reclassified. Shape-specialized
                # callers should pass score_fn_returns_dict explicitly.
                import logging

                logging.getLogger(__name__).info(
                    "DesignerAsOptimizer probe evaluation failed (%s: %s); "
                    "classifying score_fn from problem.metric_information.",
                    type(e).__name__,
                    e,
                )
                dict_scores = bool(problem.metric_information)
                probe_scored = None
        if dict_scores and not problem.metric_information:
            raise ValueError(
                "A dict-returning score_fn needs problem.metric_information "
                "to rank its metrics; pass a problem with metrics or a "
                "sequence-returning score_fn."
            )
        if dict_scores:
            metric_goals = {
                m.name: m.goal for m in problem.metric_information
            }
            inner_problem = problem
        else:
            # Single synthetic always-MAXIMIZE acquisition metric over the
            # caller's search space — the caller's own metric goals must
            # not flip the acquisition's sign.
            metric_goals = {
                "acquisition": base_study_config.ObjectiveMetricGoal.MAXIMIZE
            }
            inner_problem = base_study_config.ProblemStatement(
                search_space=problem.search_space,
                metric_information=base_study_config.MetricsConfig(
                    [
                        base_study_config.MetricInformation(
                            name="acquisition",
                            goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE,
                        )
                    ]
                ),
            )
        designer = self.designer_factory(inner_problem)
        # Drop the probe if its metric keys don't cover the ranking metrics
        # (dict-style score_fn with an empty metric_information problem).
        if probe_scored is not None and not set(metric_goals) <= set(probe_scored[0]):
            probe_scored = None
        scored = [probe_scored] if probe_scored is not None else []
        next_id = 1
        for _ in range(self.num_rounds):
            suggestions = designer.suggest(self.batch_size)
            if not suggestions:
                break
            values = score_fn(suggestions)
            if dict_scores:
                per_trial = [
                    {k: float(np.asarray(v[i]).reshape(())) for k, v in values.items()}
                    for i in range(len(suggestions))
                ]
            else:
                per_trial = [{"acquisition": float(v)} for v in values]
            completed = []
            for s, metrics in zip(suggestions, per_trial):
                t = s.to_trial(next_id)
                next_id += 1
                t.complete(trial_.Measurement(metrics=metrics))
                completed.append(t)
                scored.append((metrics, s))
            designer.update(core_lib.CompletedTrials(completed), core_lib.ActiveTrials())
        names = list(metric_goals)
        if len(names) == 1:
            sign = 1.0 if metric_goals[names[0]].is_maximize else -1.0
            scored.sort(key=lambda pair: -sign * pair[0][names[0]])
            return [s for _, s in scored[:count]]
        # Multi-metric: maximize-oriented Pareto rank, best ranks first.
        signs = np.asarray(
            [1.0 if metric_goals[n].is_maximize else -1.0 for n in names]
        )
        points = np.asarray([[m[n] for n in names] for m, _ in scored]) * signs
        ranks = multimetric.ParetoOptimalAlgorithm().pareto_rank(points)
        order = np.argsort(ranks, kind="stable")
        return [scored[i][1] for i in order[:count]]
