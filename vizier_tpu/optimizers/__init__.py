"""ARD optimizers and vectorized acquisition optimizers."""

from vizier_tpu.optimizers.base import BranchSelector, GradientFreeOptimizer
from vizier_tpu.optimizers.eagle import (
    EagleState,
    EagleStrategyConfig,
    VectorizedEagleStrategy,
)
from vizier_tpu.optimizers.lbfgs import (
    DEFAULT_RANDOM_RESTARTS,
    AdamOptimizer,
    LbfgsOptimizer,
    OptimizeResult,
)
from vizier_tpu.optimizers.lbfgsb_optimizer import DesignerAsOptimizer, LBFGSBOptimizer
from vizier_tpu.optimizers.vectorized import (
    RandomVectorizedStrategy,
    VectorizedOptimizer,
    VectorizedOptimizerResult,
    VectorizedStrategy,
    optimize_random,
)
