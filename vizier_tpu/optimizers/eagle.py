"""Vectorized Eagle (firefly) strategy — the default acquisition maximizer.

Parity with the reference ``VectorizedEagleStrategy``
(``/root/reference/vizier/_src/algorithms/optimizers/eagle_strategy.py:411,500``):
a pool of fireflies moves through scaled feature space under pairwise
attraction toward better-scoring flies and repulsion from worse ones, plus a
decaying random perturbation; exhausted flies are re-seeded. The whole state
is a flax struct and every step is pure jax — it runs inside the vectorized
optimizer's ``fori_loop`` entirely on device, and the pool axis shards over
the mesh for multi-chip sweeps.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp

from vizier_tpu.models import kernels

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class EagleStrategyConfig:
    """Knobs (defaults follow the reference ``EagleStrategyConfig``)."""

    pool_size: int = 50
    visibility: float = 0.45
    gravity: float = 1.5
    negative_gravity: float = 0.008
    perturbation: float = 0.16
    perturbation_lower_bound: float = 7e-5
    penalize_factor: float = 0.7
    mutate_normalization_type: str = "mean"
    categorical_perturbation_factor: float = 25.0
    prob_same_category_without_perturbation: float = 0.98


@flax.struct.dataclass
class EagleState:
    features: Array  # [P, Dc] in [0, 1]
    categorical: Array  # [P, Ds] int32
    rewards: Array  # [P] best score seen by each fly (-inf = unevaluated)
    perturbations: Array  # [P] current perturbation scale


@dataclasses.dataclass(frozen=True)
class VectorizedEagleStrategy:
    """Firefly ask/tell over mixed feature space."""

    num_continuous: int
    category_sizes: Tuple[int, ...]
    config: EagleStrategyConfig = EagleStrategyConfig()

    @property
    def num_categorical(self) -> int:
        return len(self.category_sizes)

    @property
    def batch_size(self) -> int:
        return self.config.pool_size

    # -- init --------------------------------------------------------------

    def _random_features(self, rng: Array, n: int) -> Tuple[Array, Array]:
        c_rng, s_rng = jax.random.split(rng)
        cont = jax.random.uniform(c_rng, (n, self.num_continuous), dtype=jnp.float32)
        if self.num_categorical:
            sizes = jnp.asarray(self.category_sizes, dtype=jnp.int32)
            u = jax.random.uniform(s_rng, (n, self.num_categorical))
            cat = jnp.minimum((u * sizes[None, :]).astype(jnp.int32), sizes[None, :] - 1)
        else:
            cat = jnp.zeros((n, 0), dtype=jnp.int32)
        return cont, cat

    def init_state(
        self, rng: Array, *, prior_features: Optional[kernels.MixedFeatures] = None
    ) -> EagleState:
        p = self.config.pool_size
        cont, cat = self._random_features(rng, p)
        if prior_features is not None and prior_features.continuous.shape[0] > 0:
            # Seed the head of the pool with prior (e.g. best observed) points.
            k = min(prior_features.continuous.shape[0], p)
            cont = cont.at[:k].set(prior_features.continuous[:k].astype(jnp.float32))
            if self.num_categorical:
                cat = cat.at[:k].set(prior_features.categorical[:k].astype(jnp.int32))
        return EagleState(
            features=cont,
            categorical=cat,
            rewards=jnp.full((p,), -jnp.inf, dtype=jnp.float32),
            perturbations=jnp.full((p,), self.config.perturbation, dtype=jnp.float32),
        )

    # -- ask ---------------------------------------------------------------

    def suggest(self, state: EagleState, rng: Array) -> kernels.MixedFeatures:
        cfg = self.config
        x = state.features  # [P, Dc]
        r = state.rewards

        # Pairwise pulls: toward better flies, away from worse ones.
        diff = x[None, :, :] - x[:, None, :]  # [P, P, Dc]: j - i
        sq_dist = jnp.sum(diff * diff, axis=-1)  # [P, P]
        better = (r[None, :] > r[:, None]).astype(jnp.float32)
        worse = 1.0 - better
        both_seen = (jnp.isfinite(r[None, :]) & jnp.isfinite(r[:, None])).astype(
            jnp.float32
        )
        scale = jnp.exp(-sq_dist / (2.0 * cfg.visibility**2 + 1e-12))
        force = both_seen * scale * (cfg.gravity * better - cfg.negative_gravity * worse)
        pull = jnp.einsum("ij,ijd->id", force, diff) / max(
            cfg.pool_size - 1, 1
        )

        p_rng, c_rng = jax.random.split(rng)
        noise = jax.random.normal(p_rng, x.shape, dtype=x.dtype)
        new_x = x + pull + state.perturbations[:, None] * noise
        new_x = jnp.clip(new_x, 0.0, 1.0)

        # Categorical proposal: keep own category w.h.p., else copy from the
        # best-rewarded fly or mutate randomly (scaled by perturbation).
        if self.num_categorical:
            sizes = jnp.asarray(self.category_sizes, dtype=jnp.int32)
            k1, k2, k3 = jax.random.split(c_rng, 3)
            best_idx = jnp.argmax(r)
            best_cat = state.categorical[best_idx][None, :]  # [1, Ds]
            mutate_prob = jnp.minimum(
                state.perturbations[:, None] * cfg.categorical_perturbation_factor, 1.0
            )  # [P, 1]
            u = jax.random.uniform(k1, state.categorical.shape)
            rand_u = jax.random.uniform(k2, state.categorical.shape)
            rand_cat = jnp.minimum(
                (rand_u * sizes[None, :]).astype(jnp.int32), sizes[None, :] - 1
            )
            copy_best = jax.random.uniform(k3, state.categorical.shape) < 0.5
            proposal = jnp.where(copy_best, best_cat, rand_cat)
            new_cat = jnp.where(u < mutate_prob, proposal, state.categorical)
        else:
            new_cat = state.categorical
        return kernels.MixedFeatures(new_x, new_cat)

    # -- tell --------------------------------------------------------------

    def update(
        self,
        state: EagleState,
        rng: Array,
        candidates: kernels.MixedFeatures,
        scores: Array,
    ) -> EagleState:
        cfg = self.config
        improved = scores > state.rewards
        features = jnp.where(improved[:, None], candidates.continuous, state.features)
        categorical = jnp.where(
            improved[:, None], candidates.categorical, state.categorical
        ) if self.num_categorical else state.categorical
        rewards = jnp.where(improved, scores, state.rewards)
        # Flies that failed to improve get their perturbation penalized.
        perturbations = jnp.where(
            improved,
            jnp.asarray(cfg.perturbation, jnp.float32),
            state.perturbations * cfg.penalize_factor,
        )

        # Re-seed exhausted flies (perturbation collapsed) — but never the
        # current best fly.
        exhausted = perturbations < cfg.perturbation_lower_bound
        best_idx = jnp.argmax(rewards)
        exhausted = exhausted & (jnp.arange(cfg.pool_size) != best_idx)
        fresh_cont, fresh_cat = self._random_features(rng, cfg.pool_size)
        features = jnp.where(exhausted[:, None], fresh_cont, features)
        if self.num_categorical:
            categorical = jnp.where(exhausted[:, None], fresh_cat, categorical)
        rewards = jnp.where(exhausted, -jnp.inf, rewards)
        perturbations = jnp.where(
            exhausted, jnp.asarray(cfg.perturbation, jnp.float32), perturbations
        )
        return EagleState(
            features=features,
            categorical=categorical,
            rewards=rewards,
            perturbations=perturbations,
        )
