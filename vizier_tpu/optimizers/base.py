"""Legacy gradient-free optimizer ABCs.

Parity with ``/root/reference/vizier/_src/algorithms/optimizers/base.py``
(``BranchSelector``, ``GradientFreeOptimizer``): the pre-vectorized
interfaces some integrations still target; the modern path is
``optimizers.vectorized``.
"""

from __future__ import annotations

import abc
from typing import Callable, Dict, List, Sequence

from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


class BranchSelector(abc.ABC):
    """Picks conditional-tree branches before continuous optimization."""

    @abc.abstractmethod
    def select_branches(
        self, problem: base_study_config.ProblemStatement, count: int
    ) -> List[Dict[str, trial_.ParameterValueTypes]]:
        ...


class GradientFreeOptimizer(abc.ABC):
    """Maximizes a batched score function over a problem's search space."""

    @abc.abstractmethod
    def optimize(
        self,
        score_fn: Callable[[Sequence[trial_.TrialSuggestion]], Sequence[float]],
        problem: base_study_config.ProblemStatement,
        *,
        count: int = 1,
    ) -> List[trial_.TrialSuggestion]:
        ...
