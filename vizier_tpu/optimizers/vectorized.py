"""Vectorized acquisition optimizer: a jitted ask-evaluate-tell loop.

Parity with the reference ``VectorizedOptimizer``
(``/root/reference/vizier/_src/algorithms/optimizers/vectorized_base.py:279``):
a strategy proposes candidate batches, the scoring function evaluates them on
device, the strategy updates, and a running top-k of the best candidates is
maintained — all inside one ``jax.lax.fori_loop`` under jit (75k evaluations
per suggest by default, zero host round-trips). The candidate batch axis is
the natural ``shard_map`` axis for multi-chip acquisition sweeps
(``vizier_tpu.parallel``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Protocol, Tuple

import flax.struct
import jax
import jax.numpy as jnp

from vizier_tpu.models import kernels

Array = jax.Array

# (features) -> [B] scores. Must be jit-traceable.
ScoreFn = Callable[[kernels.MixedFeatures], Array]


class VectorizedStrategy(Protocol):
    """Ask/tell strategy over scaled feature space [0,1]^Dc × categories."""

    def init_state(self, rng: Array, *, prior_features: Optional[kernels.MixedFeatures]):
        ...

    def suggest(self, state, rng: Array) -> kernels.MixedFeatures:
        ...

    def update(self, state, rng: Array, candidates: kernels.MixedFeatures, scores: Array):
        ...

    @property
    def batch_size(self) -> int:
        ...


class VectorizedOptimizerResult(NamedTuple):
    features: kernels.MixedFeatures  # top-k candidates [K, ...]
    scores: Array  # [K]


@dataclasses.dataclass(frozen=True)
class VectorizedOptimizer:
    """Runs a strategy for ``max_evaluations`` scores, keeps the top-k."""

    strategy: VectorizedStrategy
    max_evaluations: int = 75_000

    def __call__(
        self,
        score_fn: ScoreFn,
        rng: Array,
        *,
        count: int = 1,
        prior_features: Optional[kernels.MixedFeatures] = None,
    ) -> VectorizedOptimizerResult:
        strategy = self.strategy
        batch = strategy.batch_size
        iterations = max(self.max_evaluations // batch, 1)

        rng, init_rng = jax.random.split(rng)
        state = strategy.init_state(init_rng, prior_features=prior_features)

        def body(i, carry):
            state, best_feats, best_scores, rng = carry
            rng, s_rng, u_rng = jax.random.split(rng, 3)
            candidates = strategy.suggest(state, s_rng)
            scores = score_fn(candidates)
            scores = jnp.where(jnp.isfinite(scores), scores, -jnp.inf)
            state = strategy.update(state, u_rng, candidates, scores)
            # Merge into running top-k.
            all_scores = jnp.concatenate([best_scores, scores])
            all_cont = jnp.concatenate([best_feats.continuous, candidates.continuous])
            all_cat = jnp.concatenate([best_feats.categorical, candidates.categorical])
            top_scores, idx = jax.lax.top_k(all_scores, count)
            new_best = kernels.MixedFeatures(all_cont[idx], all_cat[idx])
            return state, new_best, top_scores, rng

        # Initialize the top-k buffer with the right static shapes.
        probe = strategy.suggest(state, rng)
        best_feats = kernels.MixedFeatures(
            jnp.zeros((count,) + probe.continuous.shape[1:], probe.continuous.dtype),
            jnp.zeros((count,) + probe.categorical.shape[1:], probe.categorical.dtype),
        )
        best_scores = jnp.full((count,), -jnp.inf, dtype=jnp.float32)

        state, best_feats, best_scores, _ = jax.lax.fori_loop(
            0, iterations, body, (state, best_feats, best_scores, rng)
        )
        return VectorizedOptimizerResult(best_feats, best_scores)


@flax.struct.dataclass
class _RandomState:
    num_continuous: int = flax.struct.field(pytree_node=False)
    num_categorical: int = flax.struct.field(pytree_node=False)


@dataclasses.dataclass(frozen=True)
class RandomVectorizedStrategy:
    """Uniform random search under the vectorized interface.

    Parity with ``random_vectorized_optimizer.py:146``.
    """

    num_continuous: int
    num_categorical: int
    category_sizes: Tuple[int, ...]
    suggestion_batch_size: int = 64

    @property
    def batch_size(self) -> int:
        return self.suggestion_batch_size

    def init_state(self, rng, *, prior_features=None):
        del rng, prior_features
        return _RandomState(self.num_continuous, self.num_categorical)

    def suggest(self, state, rng: Array) -> kernels.MixedFeatures:
        del state
        c_rng, s_rng = jax.random.split(rng)
        cont = jax.random.uniform(
            c_rng, (self.suggestion_batch_size, self.num_continuous), dtype=jnp.float32
        )
        if self.num_categorical:
            sizes = jnp.asarray(self.category_sizes, dtype=jnp.int32)
            u = jax.random.uniform(
                s_rng, (self.suggestion_batch_size, self.num_categorical)
            )
            cat = jnp.minimum((u * sizes[None, :]).astype(jnp.int32), sizes[None, :] - 1)
        else:
            cat = jnp.zeros((self.suggestion_batch_size, 0), dtype=jnp.int32)
        return kernels.MixedFeatures(cont, cat)

    def update(self, state, rng, candidates, scores):
        del rng, candidates, scores
        return state


def optimize_random(
    score_fn: ScoreFn,
    rng: Array,
    *,
    num_continuous: int,
    category_sizes: Tuple[int, ...],
    count: int = 1,
    max_evaluations: int = 10_000,
) -> VectorizedOptimizerResult:
    """Convenience: random-search acquisition maximization."""
    strategy = RandomVectorizedStrategy(
        num_continuous=num_continuous,
        num_categorical=len(category_sizes),
        category_sizes=tuple(category_sizes),
    )
    return VectorizedOptimizer(strategy, max_evaluations=max_evaluations)(
        score_fn, rng, count=count
    )
