"""ARD hyperparameter optimizers: pure-JAX L-BFGS with vmapped restarts.

TPU-first replacement for the reference's scipy-driven L-BFGS-B
(``/root/reference/vizier/_src/jax/optimizers/jaxopt_wrappers.py:113,234`` and
``optax_wrappers.py:38``): bounds are handled by the soft-clip
reparameterization (``models.params``), so plain L-BFGS suffices — the whole
multi-restart train is ONE jitted XLA program: ``vmap`` over restarts, no
host round-trips, shardable over the ``restarts`` mesh axis
(``vizier_tpu.parallel``).

The L-BFGS here is a compact hand-rolled implementation (two-loop recursion
over fixed-size history buffers + Armijo backtracking line search in a
bounded ``while_loop``). Library zoom line searches produce enormous XLA
graphs under vmap; this one keeps compile times in seconds and contains only
fixed-shape ops.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Protocol, Tuple

import jax
import jax.flatten_util
import jax.numpy as jnp
import optax

from vizier_tpu.models import params as params_lib

Array = jax.Array
Params = params_lib.Params
LossFn = Callable[[Params], Array]

# Matches the reference's published ARD budget (vizier/jax/optimizers.py:30).
DEFAULT_RANDOM_RESTARTS = 4


class OptimizeResult(NamedTuple):
    params: Params  # best (or top-k stacked) unconstrained params
    losses: Array  # [num_restarts] final losses
    best_loss: Array


class Optimizer(Protocol):
    """(loss_fn, batched inits) -> best unconstrained params + diagnostics."""

    def __call__(
        self, loss_fn: LossFn, init_batch: Params, *, best_n: Optional[int] = None
    ) -> OptimizeResult:
        ...


class _LbfgsState(NamedTuple):
    x: Array  # [n] current point
    f: Array  # scalar loss
    g: Array  # [n] gradient
    s_hist: Array  # [m, n] position diffs
    y_hist: Array  # [m, n] gradient diffs
    rho: Array  # [m] 1 / (s·y)
    k: Array  # iteration counter (int32)
    done: Array  # bool convergence flag
    t_init: Array  # initial line-search step for the next iteration
    small_count: Array  # consecutive iterations with sub-ftol decrease


def _two_loop_direction(state: _LbfgsState, memory: int) -> Array:
    """H·g via the standard two-loop recursion over the circular history."""
    q = state.g
    k = state.k
    valid_count = jnp.minimum(k, memory)

    def bwd(i, carry):
        q, alphas = carry
        # i = 0 is the newest pair.
        idx = jnp.mod(k - 1 - i, memory)
        valid = i < valid_count
        alpha = jnp.where(valid, state.rho[idx] * jnp.dot(state.s_hist[idx], q), 0.0)
        q = q - jnp.where(valid, alpha, 0.0) * state.y_hist[idx]
        alphas = alphas.at[i].set(alpha)
        return q, alphas

    q, alphas = jax.lax.fori_loop(0, memory, bwd, (q, jnp.zeros(memory, q.dtype)))

    # Initial Hessian scaling gamma = s·y / y·y of the newest pair.
    newest = jnp.mod(k - 1, memory)
    sy = jnp.dot(state.s_hist[newest], state.y_hist[newest])
    yy = jnp.dot(state.y_hist[newest], state.y_hist[newest])
    gamma = jnp.where((k > 0) & (yy > 1e-20), sy / yy, 1.0)
    r = gamma * q

    def fwd(i, r):
        # Reverse order: oldest first = i counts from the back.
        j = memory - 1 - i
        idx = jnp.mod(k - 1 - j, memory)
        valid = j < valid_count
        beta = jnp.where(valid, state.rho[idx] * jnp.dot(state.y_hist[idx], r), 0.0)
        return r + jnp.where(valid, alphas[j] - beta, 0.0) * state.s_hist[idx]

    return jax.lax.fori_loop(0, memory, fwd, r)


def lbfgs_minimize(
    loss_fn: Callable[[Array], Array],
    x0: Array,
    *,
    maxiter: int = 50,
    memory: int = 10,
    max_linesearch_steps: int = 20,
    gtol: float = 1e-5,
    ftol: float = 1e-6,
    ftol_patience: int = 2,
    armijo_c1: float = 1e-4,
) -> Tuple[Array, Array]:
    """Minimizes a flat-vector loss; returns (x, f(x)). jit/vmap-safe.

    ``ftol`` is a scipy-style relative-decrease stop: once ``ftol_patience``
    CONSECUTIVE accepted steps each improve the loss by less than
    ``ftol * max(|f|, 1)`` the run is converged (``ftol <= 0`` disables).
    The patience matters: a single small decrease can come from a step
    capped by the line-search warm start rather than a true plateau, and
    stopping there returns a bad optimum on ill-scaled problems. Without
    any ftol stop every restart burns the full ``maxiter`` budget — at
    1000 trials each iteration is a padded-1024 Cholesky, and the ARD loss
    plateaus ~25-40% before the budget (measured on the bench problem).
    """
    value_and_grad = jax.value_and_grad(loss_fn)
    f0, g0 = value_and_grad(x0)
    n = x0.shape[0]
    init = _LbfgsState(
        x=x0,
        f=f0,
        g=g0,
        s_hist=jnp.zeros((memory, n), x0.dtype),
        y_hist=jnp.zeros((memory, n), x0.dtype),
        rho=jnp.zeros((memory,), x0.dtype),
        k=jnp.asarray(0, jnp.int32),
        done=jnp.asarray(False),
        t_init=jnp.asarray(1.0, x0.dtype),
        small_count=jnp.asarray(0, jnp.int32),
    )

    def cond(state: _LbfgsState) -> Array:
        return (state.k < maxiter) & ~state.done

    def step(state: _LbfgsState) -> _LbfgsState:
        d = -_two_loop_direction(state, memory)
        # Fall back to steepest descent if d is not a descent direction.
        gd = jnp.dot(state.g, d)
        bad = (gd >= 0.0) | ~jnp.isfinite(gd)
        d = jnp.where(bad, -state.g, d)
        gd = jnp.where(bad, -jnp.dot(state.g, state.g), gd)

        # Armijo backtracking: t <- t/2 until sufficient decrease.
        def ls_cond(carry):
            t, f_new, i = carry
            insufficient = f_new > state.f + armijo_c1 * t * gd
            return (insufficient | ~jnp.isfinite(f_new)) & (i < max_linesearch_steps)

        def ls_body(carry):
            t, _, i = carry
            t = t * 0.5
            return t, loss_fn(state.x + t * d), i + 1

        # Warm-started line search: restarting at t=1 every iteration costs
        # ~6-8 halvings per iteration on ill-scaled ARD losses — each one a
        # full Cholesky (measured 291-386 line-search evals per restart on
        # the 1000x20d bench problem; the warm start cuts them to ~1-2).
        # When the warm-started t0 is accepted WITHOUT halving, larger steps
        # may have been available, so the next iteration resets to a full
        # step — otherwise a capped step cascade can stall ill-conditioned
        # runs far from the optimum.
        t0 = state.t_init
        t, f_new, num_halvings = jax.lax.while_loop(
            ls_cond, ls_body, (t0, loss_fn(state.x + t0 * d), jnp.asarray(0))
        )
        accepted = jnp.isfinite(f_new) & (f_new <= state.f)
        x_new = jnp.where(accepted, state.x + t * d, state.x)
        f_new = jnp.where(accepted, f_new, state.f)
        g_new = jnp.where(accepted, value_and_grad(x_new)[1], state.g)

        s = x_new - state.x
        y = g_new - state.g
        sy = jnp.dot(s, y)
        slot = jnp.mod(state.k, memory)
        update_hist = accepted & (sy > 1e-10)
        s_hist = jnp.where(
            update_hist, state.s_hist.at[slot].set(s), state.s_hist
        )
        y_hist = jnp.where(
            update_hist, state.y_hist.at[slot].set(y), state.y_hist
        )
        rho = jnp.where(
            update_hist, state.rho.at[slot].set(1.0 / jnp.maximum(sy, 1e-20)), state.rho
        )
        small_grad = jnp.max(jnp.abs(g_new)) < gtol
        small_decrease = (
            accepted
            & (ftol > 0.0)
            & ((state.f - f_new) <= ftol * jnp.maximum(jnp.abs(f_new), 1.0))
        )
        small_count = jnp.where(small_decrease, state.small_count + 1, 0)
        converged = small_grad | (small_count >= ftol_patience)
        unhalved = accepted & (num_halvings == 0)
        t_init_next = jnp.where(
            unhalved | ~accepted,
            jnp.asarray(1.0, state.x.dtype),
            jnp.minimum(jnp.asarray(1.0, state.x.dtype), t * 4.0),
        )
        return _LbfgsState(
            x=x_new,
            f=f_new,
            g=g_new,
            s_hist=s_hist,
            y_hist=y_hist,
            rho=rho,
            k=state.k + 1,
            done=converged | ~accepted,
            t_init=t_init_next,
            small_count=small_count,
        )

    final = jax.lax.while_loop(cond, step, init)
    return final.x, final.f


def _select_best(finals: Params, losses: Array, best_n: Optional[int]) -> OptimizeResult:
    losses = jnp.where(jnp.isfinite(losses), losses, jnp.inf)
    if best_n is None:
        best = jnp.argmin(losses)
        best_params = jax.tree_util.tree_map(lambda a: a[best], finals)
        return OptimizeResult(best_params, losses, losses[best])
    _, top_idx = jax.lax.top_k(-losses, best_n)
    top_params = jax.tree_util.tree_map(lambda a: a[top_idx], finals)
    return OptimizeResult(top_params, losses, losses[top_idx[0]])


@dataclasses.dataclass(frozen=True)
class LbfgsOptimizer:
    """Multi-restart L-BFGS, fully jitted; ``best_n`` keeps an ensemble."""

    maxiter: int = 50
    memory_size: int = 10
    max_linesearch_steps: int = 20
    gtol: float = 1e-5
    ftol: float = 1e-6  # <= 0 disables the relative-decrease stop
    ftol_patience: int = 2

    def __call__(
        self, loss_fn: LossFn, init_batch: Params, *, best_n: Optional[int] = None
    ) -> OptimizeResult:
        template = jax.tree_util.tree_map(lambda a: a[0], init_batch)
        _, unravel = jax.flatten_util.ravel_pytree(template)

        def flat_loss(x: Array) -> Array:
            return loss_fn(unravel(x))

        def run_one(init: Params) -> Tuple[Params, Array]:
            x0, _ = jax.flatten_util.ravel_pytree(init)
            x, f = lbfgs_minimize(
                flat_loss,
                x0,
                maxiter=self.maxiter,
                memory=self.memory_size,
                max_linesearch_steps=self.max_linesearch_steps,
                gtol=self.gtol,
                ftol=self.ftol,
                ftol_patience=self.ftol_patience,
            )
            return unravel(x), f

        finals, losses = jax.vmap(run_one)(init_batch)
        return _select_best(finals, losses, best_n)


@dataclasses.dataclass(frozen=True)
class AdamOptimizer:
    """Adam fallback (parity with the reference's OptaxTrain wrapper)."""

    learning_rate: float = 5e-2
    maxiter: int = 200

    def __call__(
        self, loss_fn: LossFn, init_batch: Params, *, best_n: Optional[int] = None
    ) -> OptimizeResult:
        opt = optax.adam(self.learning_rate)

        def run_single(init: Params) -> Tuple[Params, Array]:
            def step(carry, _):
                prms, state = carry
                value, grad = jax.value_and_grad(loss_fn)(prms)
                updates, state = opt.update(grad, state, prms)
                prms = optax.apply_updates(prms, updates)
                return (prms, state), value

            (final, _), _ = jax.lax.scan(
                step, (init, opt.init(init)), None, length=self.maxiter
            )
            return final, loss_fn(final)

        finals, losses = jax.vmap(run_single)(init_batch)
        return _select_best(finals, losses, best_n)


def default_optimizer() -> Optimizer:
    return LbfgsOptimizer()
