"""PyGlove integration: evolutionary/program search on the vizier service.

Parity in role with ``/root/reference/vizier/_src/pyglove/``
(``backend.py:69`` ``VizierBackend(pg.tuning.Backend)``, ``pythia.py``
``TunerPolicy``, ``converters.py`` DNA⇄Trial): PyGlove drives program
search; each DNA materializes as a vizier trial, and a PyGlove
``DNAGenerator`` runs as a Pythia policy so primary/secondary tuner
processes share one study with failover.

PyGlove is not bundled in this image, so everything importing ``pg`` is
gated: the module imports cleanly, constructing the backend without pyglove
raises a clear ImportError, and the serialized-DNA trial converters (plain
dict encoding) are testable standalone.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from vizier_tpu import pyvizier as vz
from vizier_tpu.pythia import policy as policy_lib

try:  # pragma: no cover - exercised only where pyglove is installed.
    import pyglove as pg

    PYGLOVE_AVAILABLE = True
except ImportError:  # pragma: no cover
    pg = None
    PYGLOVE_AVAILABLE = False

_DNA_KEY = "dna_spec_values"
_NS = "pyglove"

# Global registry study_name -> (dna_spec, generator). The PRIMARY tuner
# registers its generator here so the in-process policy factory can host it
# (parity with the reference's global policy cache, ``backend.py:66``).
_GENERATOR_REGISTRY: Dict[str, tuple] = {}


def register_generator(study_name: str, dna_spec, algorithm) -> None:
    _GENERATOR_REGISTRY[study_name] = (dna_spec, algorithm)


def get_registered_generator(study_name: str):
    return _GENERATOR_REGISTRY.get(study_name)


class DNATrialConverter:
    """Serialized-DNA ⇄ trial converters (pure; no pyglove required).

    DNA decision values are stored both as trial parameters (for
    observability) and as a JSON blob in metadata (for lossless recovery).
    """

    @staticmethod
    def to_suggestion(decisions: Dict[str, Any]) -> vz.TrialSuggestion:
        params = vz.ParameterDict()
        for key, value in decisions.items():
            if isinstance(value, (str, int, float, bool)):
                params[key] = value
            else:
                params[key] = json.dumps(value)
        suggestion = vz.TrialSuggestion(parameters=params)
        suggestion.metadata.ns(_NS)[_DNA_KEY] = json.dumps(decisions)
        return suggestion

    @staticmethod
    def to_decisions(trial: vz.Trial) -> Dict[str, Any]:
        raw = trial.metadata.ns(_NS).get(_DNA_KEY)
        if raw is not None:
            return json.loads(raw)
        return {k: v.value for k, v in trial.parameters.items()}


def _build_pg_dna(values) -> "pg.DNA":  # pragma: no cover - needs pyglove
    """Nested (value, children) tuples → pg.DNA tree."""

    def node(v, children):
        return pg.DNA(v, [node(*c) for c in children])

    return pg.DNA(None, [node(*c) for c in values])


class TunerPolicy(policy_lib.Policy):
    """Hosts a PyGlove DNAGenerator as a Pythia policy.

    With a structured DNASpec, trials round-trip through
    ``converters.DNASpecConverter`` (full tree: conditional candidate
    subspaces, multi-subchoices, floats); dict-DNAs keep the plain encoding.
    """

    def __init__(self, supporter, dna_spec, algorithm):
        if not PYGLOVE_AVAILABLE:
            raise ImportError("pyglove is required for TunerPolicy.")
        self._supporter = supporter
        self._dna_spec = dna_spec
        self._algorithm = algorithm  # a pg.DNAGenerator
        self._algorithm.setup(dna_spec)
        self._fed_ids: set = set()
        self._tree_converter = None
        if hasattr(dna_spec, "elements"):
            from vizier_tpu.pyglove import converters as pg_converters

            self._tree_converter = pg_converters.DNASpecConverter(dna_spec)

    @property
    def should_be_cached(self) -> bool:
        return True

    def _trial_to_dna(self, t: vz.Trial) -> "pg.DNA":
        if self._tree_converter is not None:
            dna = _build_pg_dna(self._tree_converter.to_dna_values(t))
        else:
            dna = pg.DNA(DNATrialConverter.to_decisions(t))  # type: ignore[union-attr]
        dna.use_spec(self._dna_spec)
        return dna

    def _dna_to_suggestion(self, dna) -> vz.TrialSuggestion:
        if self._tree_converter is not None:
            return self._tree_converter.to_trial_suggestion(dna)
        return DNATrialConverter.to_suggestion(dna.to_dict())

    def suggest(self, request: policy_lib.SuggestRequest) -> policy_lib.SuggestDecision:
        # Feed newly-completed FEASIBLE trials back into the generator.
        completed = self._supporter.GetTrials(status_matches=vz.TrialStatus.COMPLETED)
        for t in completed:
            if t.id in self._fed_ids or t.final_measurement is None or t.infeasible:
                continue
            metrics = t.final_measurement.metrics
            metric = metrics.get("reward") or next(iter(metrics.values()))
            self._algorithm.feedback(self._trial_to_dna(t), metric.value)
            self._fed_ids.add(t.id)
        suggestions = []
        for _ in range(request.count):
            dna = self._algorithm.propose()
            suggestions.append(self._dna_to_suggestion(dna))
        return policy_lib.SuggestDecision(suggestions=suggestions)


class VizierBackend:
    """pg.tuning backend running PyGlove search over the vizier service.

    Tuner modes mirror the reference (``backend.py:46-62``): the PRIMARY
    tuner hosts the generator; SECONDARY tuners attach to the same study and
    only evaluate — if the primary dies, any secondary can be promoted by
    re-registering the generator (state is re-fed from completed trials).
    """

    def __init__(
        self,
        study_id: str,
        dna_spec=None,
        algorithm=None,
        *,
        owner: str = "pyglove",
        endpoint: Optional[str] = None,
    ):
        if not PYGLOVE_AVAILABLE:
            raise ImportError(
                "pyglove is not installed in this environment; VizierBackend "
                "requires pyglove. DNATrialConverter works standalone."
            )
        from vizier_tpu.service import clients

        config = vz.StudyConfig(algorithm="PYGLOVE")
        config.metric_information.append(
            vz.MetricInformation(name="reward", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        self._study = clients.Study.from_study_config(
            config, owner=owner, study_id=study_id, endpoint=endpoint
        )
        self._dna_spec = dna_spec
        self._algorithm = algorithm
        if dna_spec is not None and algorithm is not None:
            # PRIMARY tuner: host the generator for the policy factory.
            register_generator(self._study.resource_name, dna_spec, algorithm)

    def next_trial(self):
        (trial,) = self._study.suggest(count=1)
        return trial

    def study(self):
        return self._study
