"""PyGlove DNASpec ⇄ vizier search-space / DNA ⇄ trial converters.

Parity with ``/root/reference/vizier/_src/pyglove/converters.py`` (DNASpec
walk ``:101-209``, ``VizierConverter.to_dna/to_trial`` ``:405-527``): PyGlove
genomes are trees — a ``Choices`` decision point holds candidate *subspaces*
whose own decision points only exist when that candidate is chosen, which is
exactly a vizier conditional search space; ``Float`` points map to scaled
double parameters and literal choice values become categorical values.

Everything here is *structural* (duck-typed against the ``pg.geno`` data
model: objects with ``elements`` / ``num_choices`` / ``candidates`` /
``literal_values`` / ``min_value`` / ``max_value``), so the logic is fully
exercised by the test double in ``tests/pyglove/`` even though pyglove
itself is not bundled in this image; with pyglove installed the same code
consumes real ``pg.DNASpec`` / ``pg.DNA`` objects unchanged.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from vizier_tpu import pyvizier as vz

_CUSTOM_PREFIX = "__custom__:"


# ---------------------------------------------------------------------------
# Structural views of the pg.geno data model (duck-typed accessors).
# ---------------------------------------------------------------------------


def _is_space(node: Any) -> bool:
    return hasattr(node, "elements")


def _is_choices(node: Any) -> bool:
    return hasattr(node, "candidates") and hasattr(node, "num_choices")


def _is_float(node: Any) -> bool:
    return hasattr(node, "min_value") and hasattr(node, "max_value")


def _location_key(node: Any, fallback: str) -> str:
    name = getattr(node, "name", None)
    if name:
        return str(name)
    location = getattr(node, "location", None)
    if location is not None and str(location):
        return str(location)
    return fallback


def _space_is_constant(space: Any) -> bool:
    return not getattr(space, "elements", ())


def _scale_type(node: Any) -> Optional[vz.ScaleType]:
    scale = getattr(node, "scale", None)
    return {
        "linear": vz.ScaleType.LINEAR,
        "log": vz.ScaleType.LOG,
        "rlog": vz.ScaleType.REVERSE_LOG,
    }.get(scale)


def _categories(choices: Any) -> List[str]:
    """One category string per candidate, guaranteed distinct.

    Non-primitive / oversized literals format as index/value pairs (the
    reference's scheme); duplicate primitive literals (distinct candidate
    subspaces with equal literal values) get the same index prefix — a
    silent first-match resolution would rebuild the wrong choice index.
    """
    literals = getattr(choices, "literal_values", None)
    n = len(choices.candidates)
    if not literals:
        return [str(i) for i in range(n)]
    out = []
    for index in range(n):
        value = literals[index]
        if not isinstance(value, (int, float, bool, str)):
            out.append(f"{index}/{value}")
            continue
        text = str(value)
        out.append(text if len(text) < 120 else f"{index}/{text[:100]}")
    if len(set(out)) != len(out):
        out = [f"{i}/{str(literals[i])[:100]}" for i in range(n)]
    return out


# ---------------------------------------------------------------------------
# DNASpec -> SearchSpace.
# ---------------------------------------------------------------------------


def to_search_space(dna_spec: Any) -> vz.SearchSpace:
    """Walks the DNASpec tree into a (possibly conditional) search space."""
    space = vz.SearchSpace()
    _add_space(space.root, dna_spec, prefix="")
    return space


def _add_space(selector, node: Any, prefix: str) -> None:
    for i, element in enumerate(getattr(node, "elements", ())):
        _add_decision_point(selector, element, prefix, i)


def _add_decision_point(selector, point: Any, prefix: str, index: int) -> None:
    key = prefix + _location_key(point, f"decision_{index}")
    if _is_choices(point):
        num_choices = int(getattr(point, "num_choices", 1) or 1)
        categories = _categories(point)
        # A k-subchoice Choices becomes k sibling categorical parameters
        # (reference `_make_decision_point`).
        for sub in range(num_choices):
            name = key if num_choices == 1 else f"{key}[{sub}]"
            param = selector.add_categorical_param(name, categories)
            for c, candidate in enumerate(point.candidates):
                if _space_is_constant(candidate):
                    continue
                # Conditional: the candidate's own decision points exist only
                # when this category is selected.
                child = param.select_values([categories[c]])
                _add_space(child, candidate, prefix=f"{name}/{c}/")
    elif _is_float(point):
        selector.add_float_param(
            key,
            float(point.min_value),
            float(point.max_value),
            scale_type=_scale_type(point) or vz.ScaleType.LINEAR,
        )
    else:
        # CustomDecisionPoint: free-form genome serialized as a string.
        selector.add_categorical_param(key, [_CUSTOM_PREFIX + "any"])


# ---------------------------------------------------------------------------
# DNA -> trial parameters and back.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DNASpecConverter:
    """Bidirectional DNA ⇄ trial-parameter mapping over one DNASpec."""

    dna_spec: Any

    def __post_init__(self):
        self.search_space = to_search_space(self.dna_spec)

    # -- DNA -> parameters --------------------------------------------------

    def dna_to_parameters(self, dna: Any) -> Dict[str, Any]:
        """Flattens a DNA tree into {parameter name: value}."""
        out: Dict[str, Any] = {}
        children = list(getattr(dna, "children", ()) or ())
        self._fill_space(self.dna_spec, children, "", out)
        return out

    def _fill_space(
        self, space: Any, dna_children: List[Any], prefix: str, out: Dict[str, Any]
    ) -> None:
        elements = list(getattr(space, "elements", ()))
        if len(dna_children) != len(elements):
            raise ValueError(
                f"DNA has {len(dna_children)} children for a space of "
                f"{len(elements)} decision points at {prefix!r}."
            )
        for i, (element, child) in enumerate(zip(elements, dna_children)):
            self._fill_point(element, child, prefix, i, out)

    def _fill_point(
        self, point: Any, dna: Any, prefix: str, index: int, out: Dict[str, Any]
    ) -> None:
        key = prefix + _location_key(point, f"decision_{index}")
        if _is_choices(point):
            num_choices = int(getattr(point, "num_choices", 1) or 1)
            if num_choices == 1:
                picks = [dna]
            else:
                picks = list(getattr(dna, "children", ()) or ())
                if len(picks) != num_choices:
                    raise ValueError(
                        f"{key}: expected {num_choices} subchoices, got "
                        f"{len(picks)}."
                    )
            for sub, pick in enumerate(picks):
                name = key if num_choices == 1 else f"{key}[{sub}]"
                choice = int(pick.value)
                out[name] = _categories(point)[choice]
                candidate = point.candidates[choice]
                if not _space_is_constant(candidate):
                    self._fill_space(
                        candidate,
                        list(getattr(pick, "children", ()) or ()),
                        f"{name}/{choice}/",
                        out,
                    )
        elif _is_float(point):
            out[key] = float(dna.value)
        else:
            out[key] = _CUSTOM_PREFIX + json.dumps(getattr(dna, "value", None))

    # -- parameters -> DNA values -------------------------------------------

    def parameters_to_dna_values(self, parameters: Dict[str, Any]) -> Any:
        """Rebuilds the nested DNA value tree from flat trial parameters.

        Returns a nested structure of plain values ([choice index | float |
        custom payload], children...) suitable for ``pg.DNA``-style
        construction: each node is ``(value, [children])``.
        """
        getter = {
            k: (v.value if hasattr(v, "value") else v)
            for k, v in dict(parameters).items()
        }
        return self._rebuild_space(self.dna_spec, "", getter)

    def _rebuild_space(self, space: Any, prefix: str, params) -> List[Tuple]:
        out = []
        for i, element in enumerate(getattr(space, "elements", ())):
            out.extend(self._rebuild_point(element, prefix, i, params))
        return out

    def _rebuild_point(self, point: Any, prefix: str, index: int, params) -> List[Tuple]:
        key = prefix + _location_key(point, f"decision_{index}")
        if _is_choices(point):
            num_choices = int(getattr(point, "num_choices", 1) or 1)
            picks = []
            for sub in range(num_choices):
                name = key if num_choices == 1 else f"{key}[{sub}]"
                if name not in params:
                    raise ValueError(f"Missing decision {name!r} in parameters.")
                value = str(params[name])
                categories = _categories(point)
                try:
                    choice = categories.index(value)
                except ValueError as e:
                    raise ValueError(
                        f"{name}: {value!r} is not a candidate literal."
                    ) from e
                candidate = point.candidates[choice]
                children = (
                    []
                    if _space_is_constant(candidate)
                    else self._rebuild_space(candidate, f"{name}/{choice}/", params)
                )
                picks.append((choice, children))
            if num_choices == 1:
                return picks
            return [(None, picks)]  # multi-choice container node
        if _is_float(point):
            if key not in params:
                raise ValueError(f"Missing decision {key!r} in parameters.")
            return [(float(params[key]), [])]
        raw = str(params.get(key, _CUSTOM_PREFIX + "null"))
        payload = raw[len(_CUSTOM_PREFIX):] if raw.startswith(_CUSTOM_PREFIX) else raw
        return [(json.loads(payload) if payload != "any" else None, [])]

    # -- trial plumbing -----------------------------------------------------

    def to_trial_suggestion(self, dna: Any) -> vz.TrialSuggestion:
        params = self.dna_to_parameters(dna)
        suggestion = vz.TrialSuggestion(parameters=params)
        suggestion.metadata.ns("pyglove")["dna_spec_values"] = json.dumps(
            params, default=str
        )
        return suggestion

    def to_dna_values(self, trial: vz.Trial) -> List[Tuple]:
        raw = trial.metadata.ns("pyglove").get("dna_spec_values")
        params = json.loads(raw) if raw is not None else trial.parameters
        return self.parameters_to_dna_values(params)
