"""vizier-tpu: a TPU-native black-box optimization (Vizier) framework.

A from-scratch, JAX/XLA-first re-design of the capabilities of OSS Vizier
(google/vizier): a study/trial service, a Pythia algorithm-hosting protocol,
and a Gaussian-Process-Bandit suggestion stack whose numerical core runs as
jit-compiled XLA programs on TPU, sharded over device meshes with
``jax.sharding`` + ``shard_map``.

Public namespaces (mirroring the reference facade layout,
``/root/reference/vizier/__init__.py``):

- ``vizier_tpu.pyvizier``   — shared data model (search spaces, trials, ...)
- ``vizier_tpu.pythia``     — algorithm-hosting protocol (Policy, supporters)
- ``vizier_tpu.algorithms`` — Designer abstractions + designer→policy wrappers
- ``vizier_tpu.designers``  — the algorithm zoo (GP bandit, eagle, NSGA-II, ...)
- ``vizier_tpu.models``     — JAX stochastic-process models (GP kernels, ARD)
- ``vizier_tpu.ops``        — XLA/Pallas numerical kernels (pareto, distances)
- ``vizier_tpu.optimizers`` — ARD optimizers + vectorized acquisition optimizers
- ``vizier_tpu.parallel``   — device-mesh sharding utilities (ICI data plane)
- ``vizier_tpu.converters`` — trial⇄array converters, padded types
- ``vizier_tpu.service``    — gRPC/in-process study service, datastores, clients
- ``vizier_tpu.benchmarks`` — experimenters, runners, convergence analyzers
"""

__version__ = "0.1.0"
