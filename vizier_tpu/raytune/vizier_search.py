"""Ray Tune integration: ``VizierSearch`` searcher.

Parity with ``/root/reference/vizier/_src/raytune/vizier_search.py:32`` and
``converters.py``: a ``ray.tune.search.Searcher`` backed by the vizier-tpu
study service. The whole behavioral contract (suggest / result / complete /
save / restore / late property binding) is ray-free and tested against the
in-process service; ray — absent from this image — is only needed as the
base class when plugging into a real ``tune.Tuner``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from vizier_tpu import pyvizier as vz
from vizier_tpu.service import clients

try:  # pragma: no cover - exercised only where ray is installed.
    from ray.tune.search import Searcher as _RaySearcher

    _RAY_AVAILABLE = True
except ImportError:  # pragma: no cover
    _RaySearcher = object
    _RAY_AVAILABLE = False


class SearchSpaceConverter:
    """Ray Tune param_space dict → vizier SearchSpace."""

    @staticmethod
    def to_vizier(param_space: Dict[str, Any]) -> vz.SearchSpace:
        space = vz.SearchSpace()
        root = space.root
        for name, domain in param_space.items():
            if isinstance(domain, dict):  # plain-dict mini-language
                kind = domain.get("type")
                if kind == "uniform":
                    root.add_float_param(name, domain["min"], domain["max"])
                elif kind == "loguniform":
                    root.add_float_param(
                        name, domain["min"], domain["max"], scale_type=vz.ScaleType.LOG
                    )
                elif kind == "randint":
                    root.add_int_param(name, domain["min"], domain["max"])
                elif kind == "choice":
                    values = domain["values"]
                    if all(isinstance(v, str) for v in values):
                        root.add_categorical_param(name, values)
                    else:
                        root.add_discrete_param(name, values)
                else:
                    raise ValueError(f"Unknown domain type {kind!r} for {name!r}.")
                continue
            # Ray Domain objects (duck-typed to avoid a hard ray dependency).
            cls = type(domain).__name__
            if cls == "Float":
                sampler = type(getattr(domain, "sampler", None)).__name__
                scale = vz.ScaleType.LOG if "LogUniform" in sampler else vz.ScaleType.LINEAR
                root.add_float_param(name, domain.lower, domain.upper, scale_type=scale)
            elif cls == "Integer":
                root.add_int_param(name, domain.lower, domain.upper - 1)
            elif cls == "Categorical":
                values = list(domain.categories)
                if all(isinstance(v, str) for v in values):
                    root.add_categorical_param(name, values)
                else:
                    root.add_discrete_param(name, values)
            else:
                raise ValueError(f"Unsupported ray domain {cls!r} for {name!r}.")
        return space


class VizierSearch(_RaySearcher):
    """ray.tune Searcher delegating suggestions to a vizier-tpu study.

    The full ``Searcher`` behavioral contract — ``suggest`` /
    ``on_trial_result`` / ``on_trial_complete`` / ``save`` / ``restore`` /
    late ``set_search_properties`` binding — is implemented without any ray
    dependency (and covered by tests against the in-process service); with
    ray installed the class plugs straight into ``tune.Tuner`` as its base
    class becomes ``ray.tune.search.Searcher``.
    """

    def __init__(
        self,
        param_space: Optional[Dict[str, Any]] = None,
        *,
        metric: Optional[str] = None,
        mode: str = "max",
        algorithm: str = "DEFAULT",
        owner: str = "raytune",
        study_id: Optional[str] = None,
        **kwargs,
    ):
        if _RAY_AVAILABLE:
            super().__init__(metric=metric, mode=mode, **kwargs)
        self._metric = metric
        self._mode = mode
        self._algorithm = algorithm
        self._owner = owner
        self._study_id = study_id
        self._study = None
        self._ray_to_vizier: Dict[str, int] = {}
        if param_space is not None and metric is not None:
            self._create_study(param_space)

    def _create_study(self, param_space: Dict[str, Any]) -> None:
        goal = (
            vz.ObjectiveMetricGoal.MAXIMIZE
            if self._mode == "max"
            else vz.ObjectiveMetricGoal.MINIMIZE
        )
        config = vz.StudyConfig(algorithm=self._algorithm)
        config.search_space = SearchSpaceConverter.to_vizier(param_space)
        config.metric_information.append(
            vz.MetricInformation(name=self._metric, goal=goal)
        )
        self._study = clients.Study.from_study_config(
            config, owner=self._owner, study_id=self._study_id
        )

    def set_search_properties(
        self, metric: Optional[str], mode: Optional[str], config: Dict, **spec
    ) -> bool:
        """Late binding: ray calls this when the Tuner supplies the space."""
        if self._study is not None:
            return False
        if metric:
            self._metric = metric
        if mode:
            self._mode = mode
        if self._metric is None or not config:
            return False
        self._create_study(config)
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._study is None:
            return None  # ray contract: None = not ready / finished
        trials = self._study.suggest(count=1, client_id=trial_id)
        if not trials:  # exhausted finite space: signal completion, not crash
            return None
        (trial,) = trials
        self._ray_to_vizier[trial_id] = trial.id
        return dict(trial.parameters)

    def on_trial_complete(
        self, trial_id: str, result: Optional[Dict] = None, error: bool = False
    ) -> None:
        uid = self._ray_to_vizier.pop(trial_id, None)
        if uid is None or self._study is None:
            return
        trial = self._study.get_trial(uid)
        if error or result is None or self._metric not in result:
            trial.complete(infeasible_reason="ray trial errored")
        else:
            trial.complete(
                vz.Measurement(metrics={self._metric: float(result[self._metric])})
            )

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        uid = self._ray_to_vizier.get(trial_id)
        if uid is not None and self._study is not None and self._metric in result:
            self._study.get_trial(uid).add_measurement(
                vz.Measurement(
                    metrics={self._metric: float(result[self._metric])},
                    steps=float(result.get("training_iteration", 0)),
                )
            )

    # -- checkpointing (ray Searcher save/restore contract) -----------------

    def save(self, checkpoint_path: str) -> None:
        """Persists the ray↔vizier trial map + study pointer; study state
        itself lives in the vizier service (restart-transparent)."""
        import json

        state = {
            "ray_to_vizier": self._ray_to_vizier,
            "study_resource_name": (
                self._study.resource_name if self._study is not None else None
            ),
            "metric": self._metric,
            "mode": self._mode,
        }
        with open(checkpoint_path, "w") as f:
            json.dump(state, f)

    def restore(self, checkpoint_path: str) -> None:
        import json

        with open(checkpoint_path) as f:
            state = json.load(f)
        self._ray_to_vizier = {k: int(v) for k, v in state["ray_to_vizier"].items()}
        self._metric = state["metric"]
        self._mode = state["mode"]
        if state["study_resource_name"]:
            self._study = clients.Study.from_resource_name(
                state["study_resource_name"]
            )
