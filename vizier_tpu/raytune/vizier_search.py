"""Ray Tune integration: ``VizierSearch`` searcher.

Parity with ``/root/reference/vizier/_src/raytune/vizier_search.py:32`` and
``converters.py``: a ``ray.tune.search.Searcher`` backed by the vizier-tpu
study service. Ray is not bundled in this image, so the module degrades to a
clear ImportError at construction time while remaining importable (the
search-space converter is pure and fully testable without ray).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from vizier_tpu import pyvizier as vz
from vizier_tpu.service import clients

try:  # pragma: no cover - exercised only where ray is installed.
    from ray.tune.search import Searcher as _RaySearcher

    _RAY_AVAILABLE = True
except ImportError:  # pragma: no cover
    _RaySearcher = object
    _RAY_AVAILABLE = False


class SearchSpaceConverter:
    """Ray Tune param_space dict → vizier SearchSpace."""

    @staticmethod
    def to_vizier(param_space: Dict[str, Any]) -> vz.SearchSpace:
        space = vz.SearchSpace()
        root = space.root
        for name, domain in param_space.items():
            if isinstance(domain, dict):  # plain-dict mini-language
                kind = domain.get("type")
                if kind == "uniform":
                    root.add_float_param(name, domain["min"], domain["max"])
                elif kind == "loguniform":
                    root.add_float_param(
                        name, domain["min"], domain["max"], scale_type=vz.ScaleType.LOG
                    )
                elif kind == "randint":
                    root.add_int_param(name, domain["min"], domain["max"])
                elif kind == "choice":
                    values = domain["values"]
                    if all(isinstance(v, str) for v in values):
                        root.add_categorical_param(name, values)
                    else:
                        root.add_discrete_param(name, values)
                else:
                    raise ValueError(f"Unknown domain type {kind!r} for {name!r}.")
                continue
            # Ray Domain objects (duck-typed to avoid a hard ray dependency).
            cls = type(domain).__name__
            if cls == "Float":
                sampler = type(getattr(domain, "sampler", None)).__name__
                scale = vz.ScaleType.LOG if "LogUniform" in sampler else vz.ScaleType.LINEAR
                root.add_float_param(name, domain.lower, domain.upper, scale_type=scale)
            elif cls == "Integer":
                root.add_int_param(name, domain.lower, domain.upper - 1)
            elif cls == "Categorical":
                values = list(domain.categories)
                if all(isinstance(v, str) for v in values):
                    root.add_categorical_param(name, values)
                else:
                    root.add_discrete_param(name, values)
            else:
                raise ValueError(f"Unsupported ray domain {cls!r} for {name!r}.")
        return space


class VizierSearch(_RaySearcher):
    """ray.tune Searcher delegating suggestions to a vizier-tpu study."""

    def __init__(
        self,
        param_space: Dict[str, Any],
        *,
        metric: str,
        mode: str = "max",
        algorithm: str = "DEFAULT",
        **kwargs,
    ):
        if not _RAY_AVAILABLE:
            raise ImportError(
                "ray is not installed in this environment; VizierSearch requires "
                "ray[tune]. The SearchSpaceConverter works standalone."
            )
        super().__init__(metric=metric, mode=mode, **kwargs)
        goal = (
            vz.ObjectiveMetricGoal.MAXIMIZE
            if mode == "max"
            else vz.ObjectiveMetricGoal.MINIMIZE
        )
        config = vz.StudyConfig(algorithm=algorithm)
        config.search_space = SearchSpaceConverter.to_vizier(param_space)
        config.metric_information.append(
            vz.MetricInformation(name=metric, goal=goal)
        )
        self._study = clients.Study.from_study_config(config, owner="raytune")
        self._ray_to_vizier: Dict[str, int] = {}
        self._metric = metric

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        (trial,) = self._study.suggest(count=1, client_id=trial_id)
        self._ray_to_vizier[trial_id] = trial.id
        return dict(trial.parameters)

    def on_trial_complete(
        self, trial_id: str, result: Optional[Dict] = None, error: bool = False
    ) -> None:
        uid = self._ray_to_vizier.pop(trial_id, None)
        if uid is None:
            return
        trial = self._study.get_trial(uid)
        if error or result is None or self._metric not in result:
            trial.complete(infeasible_reason="ray trial errored")
        else:
            trial.complete(
                vz.Measurement(metrics={self._metric: float(result[self._metric])})
            )

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        uid = self._ray_to_vizier.get(trial_id)
        if uid is not None and self._metric in result:
            self._study.get_trial(uid).add_measurement(
                vz.Measurement(
                    metrics={self._metric: float(result[self._metric])},
                    steps=float(result.get("training_iteration", 0)),
                )
            )
