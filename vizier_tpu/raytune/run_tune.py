"""Helpers for running Ray Tune over this framework's experimenters.

Parity with ``/root/reference/vizier/_src/raytune/run_tune.py:33,54,87``
(``run_tune_distributed``, ``run_tune_bbob``, ``run_tune_from_factory``).
The experimenter→(param_space, objective) plumbing is ray-free and tested;
the ``tune.Tuner`` drive itself is gated on ray, which is absent from this
image.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from vizier_tpu.benchmarks.experimenters import base as experimenters_base
from vizier_tpu.benchmarks.experimenters import experimenter_factory
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

try:  # pragma: no cover - exercised only where ray is installed.
    from ray import air, data, tune

    _RAY_AVAILABLE = True
except ImportError:  # pragma: no cover
    air = data = tune = None
    _RAY_AVAILABLE = False


def experimenter_param_space(
    experimenter: experimenters_base.Experimenter,
) -> Dict[str, Any]:
    """Search space as the plain-dict mini-language ``SearchSpaceConverter`` maps.

    (Ray's own ``tune.uniform`` etc. objects require ray; the dict form is
    accepted by both this module and ``raytune.vizier_search``.)
    """
    from vizier_tpu.pyvizier import parameter_config as pc

    out: Dict[str, Any] = {}
    for config in experimenter.problem_statement().search_space.parameters:
        if config.type == pc.ParameterType.DOUBLE:
            lo, hi = config.bounds
            kind = (
                "loguniform" if config.scale_type == pc.ScaleType.LOG else "uniform"
            )
            out[config.name] = {"type": kind, "min": lo, "max": hi}
        elif config.type == pc.ParameterType.INTEGER:
            lo, hi = config.bounds
            out[config.name] = {"type": "randint", "min": int(lo), "max": int(hi)}
        else:
            out[config.name] = {
                "type": "choice",
                "values": list(config.feasible_values),
            }
    return out


def experimenter_objective(
    experimenter: experimenters_base.Experimenter,
) -> Callable[[Dict[str, Any]], Dict[str, float]]:
    """config-dict → {metric: value} callable over one experimenter evaluate."""
    problem = experimenter.problem_statement()

    def objective(config: Dict[str, Any]) -> Dict[str, float]:
        t = trial_.Trial(id=1, parameters=dict(config))
        experimenter.evaluate([t])
        if t.final_measurement is None:
            return {m.name: float("nan") for m in problem.metric_information}
        return {
            name: metric.value
            for name, metric in t.final_measurement.metrics.items()
        }

    return objective


def run_tune_from_factory(
    factory: Callable[[], experimenters_base.Experimenter],
    tune_config=None,
    run_config=None,
):
    """Fits a ``tune.Tuner`` on the factory's experimenter (requires ray)."""
    if not _RAY_AVAILABLE:  # pragma: no cover
        raise ImportError("ray is not installed; run_tune_from_factory needs it.")
    experimenter = factory()
    problem = experimenter.problem_statement()
    param_space = experimenter_param_space(experimenter)
    objective = experimenter_objective(experimenter)

    metric_info = problem.metric_information.item()
    if tune_config is None:
        tune_config = tune.TuneConfig()
    tune_config.metric = metric_info.name
    tune_config.mode = (
        "min"
        if metric_info.goal == base_study_config.ObjectiveMetricGoal.MINIMIZE
        else "max"
    )

    def objective_fn(config):  # pragma: no cover - needs ray workers
        from ray.air import session

        for _ in range(tune_config.num_samples):
            session.report(objective(config))

    tuner = tune.Tuner(
        objective_fn,
        param_space=param_space,
        run_config=run_config,
        tune_config=tune_config,
    )
    return tuner.fit()


def run_tune_bbob(
    function_name: str,
    dimension: int,
    shift: Optional[np.ndarray] = None,
    tune_config=None,
    run_config=None,
):
    """Fits a Ray tuner on a (optionally shifted) BBOB function (requires ray)."""
    factory = experimenter_factory.SingleObjectiveExperimenterFactory(
        name=function_name, dim=dimension, shift=shift
    )
    return run_tune_from_factory(factory, tune_config, run_config)


def run_tune_distributed(
    run_tune_args_list: List[Tuple[Any, ...]],
    run_tune: Callable[..., Any],
) -> Sequence[Any]:
    """Maps run_tune over arg tuples via the Ray datasets API (requires ray)."""
    if not _RAY_AVAILABLE:  # pragma: no cover
        raise ImportError("ray is not installed; run_tune_distributed needs it.")
    ds = data.from_items([{"args_tuple": args} for args in run_tune_args_list])
    ds = ds.map(lambda x: {"result": run_tune(*x["args_tuple"])})
    return ds.take_all()
