"""XLA Pareto-frontier and hypervolume ops.

Parity with ``/root/reference/vizier/_src/jax/xla_pareto.py:27-192`` and the
numpy multimetric algorithms
(``/root/reference/vizier/_src/pyvizier/multimetric/pareto_optimal.py``,
``hypervolume.py``): domination tests, frontier masks, Pareto rank, crowding
distance (NSGA-II), and the random-direction cumulative hypervolume — all
batched jax.numpy (MAXIMIZE convention) so they run on device and vmap.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def dominates(a: Array, b: Array) -> Array:
    """True where point a dominates b (a >= b everywhere, > somewhere)."""
    return jnp.all(a >= b, axis=-1) & jnp.any(a > b, axis=-1)


def domination_matrix(points: Array) -> Array:
    """[N, M] -> [N, N] bool: entry (i, j) = point i dominates point j."""
    return dominates(points[:, None, :], points[None, :, :])


def is_frontier(points: Array, *, valid_mask: Optional[Array] = None) -> Array:
    """[N, M] -> [N] bool: True where no valid point dominates this one."""
    dom = domination_matrix(points)  # dom[i, j]: i dominates j
    if valid_mask is not None:
        dom = dom & valid_mask[:, None]
    dominated = jnp.any(dom, axis=0)
    frontier = ~dominated
    if valid_mask is not None:
        frontier = frontier & valid_mask
    return frontier


def pareto_rank(points: Array, *, valid_mask: Optional[Array] = None) -> Array:
    """[N, M] -> [N] int: number of valid points dominating each point.

    Rank 0 = frontier. (The count-based rank of the reference's
    ``jax_pareto_rank``; NSGA-II's layered sort uses ``nondomination_layers``.)
    """
    dom = domination_matrix(points)
    if valid_mask is not None:
        dom = dom & valid_mask[:, None]
    rank = jnp.sum(dom, axis=0)
    if valid_mask is not None:
        rank = jnp.where(valid_mask, rank, points.shape[0])
    return rank


def nondomination_layers(points: Array, *, valid_mask: Optional[Array] = None) -> Array:
    """[N, M] -> [N] int: NSGA-II front index (0 = first front).

    Peeling loop over at most N fronts, as a bounded ``fori_loop``.
    """
    n = points.shape[0]
    dom = domination_matrix(points)
    if valid_mask is not None:
        dom = dom & valid_mask[:, None] & valid_mask[None, :]

    def body(i, state):
        layers, remaining = state
        # Points not dominated by any *remaining* point form the next front.
        dominated = jnp.any(dom & remaining[:, None], axis=0)
        front = remaining & ~dominated
        layers = jnp.where(front, i, layers)
        remaining = remaining & ~front
        return layers, remaining

    init_remaining = (
        valid_mask if valid_mask is not None else jnp.ones(n, dtype=bool)
    )
    layers, _ = jax.lax.fori_loop(
        0, n, body, (jnp.full((n,), n, dtype=jnp.int32), init_remaining)
    )
    return layers


def crowding_distance(
    points: Array, layers: Array, *, valid_mask: Optional[Array] = None
) -> Array:
    """[N, M] NSGA-II crowding distance within each nondomination layer."""
    n, m = points.shape
    if valid_mask is None:
        valid_mask = jnp.ones(n, dtype=bool)
    inf = jnp.asarray(jnp.inf, points.dtype)
    total = jnp.zeros(n, points.dtype)
    for j in range(m):  # static objective count
        vals = points[:, j]
        # Sort within the whole set; same-layer neighbors found via masking.
        big = jnp.where(valid_mask, vals, inf)
        order = jnp.argsort(big)
        sorted_vals = vals[order]
        sorted_layers = layers[order]
        span = jnp.maximum(jnp.max(jnp.where(valid_mask, vals, -inf)) -
                           jnp.min(jnp.where(valid_mask, vals, inf)), 1e-12)
        # Neighbor gaps among same-layer points: approximate with adjacent
        # sorted entries of the same layer.
        prev_gap = jnp.concatenate([jnp.asarray([jnp.inf], points.dtype),
                                    sorted_vals[1:] - sorted_vals[:-1]])
        next_gap = jnp.concatenate([sorted_vals[1:] - sorted_vals[:-1],
                                    jnp.asarray([jnp.inf], points.dtype)])
        same_prev = jnp.concatenate(
            [jnp.asarray([False]), sorted_layers[1:] == sorted_layers[:-1]]
        )
        same_next = jnp.concatenate(
            [sorted_layers[1:] == sorted_layers[:-1], jnp.asarray([False])]
        )
        contrib = (
            jnp.where(same_prev, prev_gap, inf) + jnp.where(same_next, next_gap, inf)
        ) / span
        # Scatter back to original order.
        unsorted = jnp.zeros(n, points.dtype).at[order].set(contrib)
        total = total + unsorted
    return jnp.where(valid_mask, total, -inf)


@functools.partial(jax.jit, static_argnames=("num_vectors",))
def cum_hypervolume_origin(
    points: Array,
    rng: Array,
    *,
    num_vectors: int = 1000,
    valid_mask: Optional[Array] = None,
) -> Array:
    """Cumulative random-scalarization hypervolume w.r.t. the origin.

    Parity with ``jax_cum_hypervolume_origin`` (``xla_pareto.py:192``):
    approximates HV(points[:i+1]) for every prefix i via random direction
    vectors — ``hv ≈ c_m * E_v[ max_i min_j (points[i, j] / v[j])_+^m ]``.
    Points must be >= 0 (translate by the reference point first).
    """
    n, m = points.shape
    # Random positive directions on the unit sphere.
    v = jnp.abs(jax.random.normal(rng, (num_vectors, m), dtype=points.dtype))
    v = v / jnp.linalg.norm(v, axis=-1, keepdims=True)
    # ratios[k, i] = min_j points[i, j] / v[k, j]
    ratios = jnp.min(points[None, :, :] / v[:, None, :], axis=-1)
    ratios = jnp.maximum(ratios, 0.0)
    if valid_mask is not None:
        ratios = jnp.where(valid_mask[None, :], ratios, 0.0)
    # Prefix max over points → cumulative coverage per direction.
    prefix = jax.lax.cummax(ratios, axis=1)  # [K, N]
    powered = prefix**m
    mean = jnp.mean(powered, axis=0)  # [N]
    # Constant c_m: volume factor for the m-dim positive orthant sphere
    # sampling = pi^(m/2) / (2^m * Gamma(m/2 + 1)).
    import math

    c_m = math.pi ** (m / 2) / (2**m * math.gamma(m / 2 + 1))
    return c_m * mean


def hypervolume(
    points: Array,
    origin: Optional[Array] = None,
    *,
    rng: Optional[Array] = None,
    num_vectors: int = 1000,
    valid_mask: Optional[Array] = None,
) -> Array:
    """Scalar HV estimate of the full set w.r.t. ``origin`` (default 0)."""
    if origin is not None:
        points = points - origin[None, :]
    points = jnp.maximum(points, 0.0)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return cum_hypervolume_origin(
        points, rng, num_vectors=num_vectors, valid_mask=valid_mask
    )[-1]
