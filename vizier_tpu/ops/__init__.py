"""Batched XLA numerical ops (Pareto domination, hypervolume)."""

from vizier_tpu.ops.pareto import (
    crowding_distance,
    cum_hypervolume_origin,
    domination_matrix,
    hypervolume,
    is_frontier,
    nondomination_layers,
    pareto_rank,
)
