"""Pallas TPU kernel: fused ARD squared-distance + Matern-5/2.

The hot op of every GP prediction and acquisition sweep is the cross-kernel
matrix ``K[Q, N] = amp² · matern52(Σ_d ((q_d − x_d)/l_d)²)``. The stock
jax.numpy path materializes a ``[Q, N, D]`` difference tensor in HBM; this
kernel tiles ``(Q, N)`` into VMEM blocks and accumulates the scaled squared
distance dimension-by-dimension on the VPU, fusing the Matern transform into
the same pass — no ``[Q, N, D]`` intermediate ever exists.

Exact (no matmul-expansion f32 cancellation), mask-aware via zeroed inverse
length scales. Falls back transparently: ``kernels.matern52_ard`` routes
here only on TPU backends for large-enough problems.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_SQRT5 = 2.2360679774997896
_BLOCK_Q = 128
_BLOCK_N = 128


def _matern_kernel_body(q_ref, x_ref, inv_ref, amp_ref, out_ref):
    """One (BLOCK_Q, BLOCK_N) tile: accumulate sq-dist over D, then matern."""
    q = q_ref[:]  # [BQ, D]
    x = x_ref[:]  # [BN, D]
    inv = inv_ref[:]  # [1, D] inverse length scales (0 for masked dims)
    d = q.shape[-1]

    def body(i, acc):
        diff = q[:, i][:, None] * inv[0, i] - x[:, i][None, :] * inv[0, i]
        return acc + diff * diff

    sq = jax.lax.fori_loop(
        0, d, body, jnp.zeros((q.shape[0], x.shape[0]), jnp.float32)
    )
    r = jnp.sqrt(jnp.maximum(sq, 1e-20))
    amp = amp_ref[0, 0]
    out_ref[:] = (
        amp * amp * (1.0 + _SQRT5 * r + (5.0 / 3.0) * sq) * jnp.exp(-_SQRT5 * r)
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def matern52_ard_continuous_pallas(
    q: jax.Array,  # [Q, D] float32
    x: jax.Array,  # [N, D] float32
    inv_length_scales: jax.Array,  # [D] (0 where dim is masked)
    amplitude: jax.Array,  # scalar
    *,
    interpret: bool = False,
) -> jax.Array:
    """[Q, N] fused ARD Matern-5/2 over continuous features."""
    qn, d = q.shape
    n = x.shape[0]
    # Pad Q/N up to block multiples (padding rows produce garbage values the
    # caller slices away; they never alias real entries).
    q_pad = -(-qn // _BLOCK_Q) * _BLOCK_Q
    n_pad = -(-n // _BLOCK_N) * _BLOCK_N
    q_full = jnp.zeros((q_pad, d), jnp.float32).at[:qn].set(q)
    x_full = jnp.zeros((n_pad, d), jnp.float32).at[:n].set(x)
    inv2d = inv_length_scales.reshape(1, d).astype(jnp.float32)
    amp2d = jnp.reshape(amplitude.astype(jnp.float32), (1, 1))

    out = pl.pallas_call(
        _matern_kernel_body,
        out_shape=jax.ShapeDtypeStruct((q_pad, n_pad), jnp.float32),
        grid=(q_pad // _BLOCK_Q, n_pad // _BLOCK_N),
        in_specs=[
            pl.BlockSpec((_BLOCK_Q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((_BLOCK_N, d), lambda i, j: (j, 0)),
            pl.BlockSpec((1, d), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_Q, _BLOCK_N), lambda i, j: (i, j)),
        interpret=interpret,
    )(q_full, x_full, inv2d, amp2d)
    return out[:qn, :n]


def _jnp_reference(
    q: jax.Array, x: jax.Array, inv: jax.Array, amplitude: jax.Array
) -> jax.Array:
    """Differentiable jnp twin of the kernel (used for the VJP)."""
    diff = q[:, None, :] * inv[None, None, :] - x[None, :, :] * inv[None, None, :]
    sq = jnp.sum(diff * diff, axis=-1)
    r = jnp.sqrt(jnp.maximum(sq, 1e-20))
    return (
        amplitude
        * amplitude
        * (1.0 + _SQRT5 * r + (5.0 / 3.0) * sq)
        * jnp.exp(-_SQRT5 * r)
    )


@jax.custom_vjp
def matern52_ard_continuous_fused(
    q: jax.Array, x: jax.Array, inv: jax.Array, amplitude: jax.Array
) -> jax.Array:
    """Pallas forward with a jnp-derived VJP — safe inside value_and_grad.

    The ARD likelihood differentiates the Gram matrix; ``pallas_call`` has
    no transpose rule, so the backward pass re-derives gradients from the
    (mathematically identical) jnp implementation.
    """
    return matern52_ard_continuous_pallas(q, x, inv, amplitude)


def _fused_fwd(q, x, inv, amplitude):
    return matern52_ard_continuous_pallas(q, x, inv, amplitude), (q, x, inv, amplitude)


def _fused_bwd(residuals, g):
    _, vjp = jax.vjp(_jnp_reference, *residuals)
    return vjp(g)


matern52_ard_continuous_fused.defvjp(_fused_fwd, _fused_bwd)


def is_tpu_backend() -> bool:
    """Whether the (already-initialized) default backend is a TPU.

    Only call from code that already holds device arrays — on a dead TPU
    tunnel, *initializing* the backend blocks, but paths that reach kernel
    computation have always initialized it already.
    """
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
