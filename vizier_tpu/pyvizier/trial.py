"""Trials, measurements, and parameter values.

Functional parity with the reference's trial module
(``/root/reference/vizier/_src/pyvizier/shared/trial.py:91,128,276,404,439``):
typed ``ParameterValue`` with casting, ``Measurement`` (metrics + steps +
elapsed time), the ``Trial`` lifecycle state machine
(REQUESTED → ACTIVE → STOPPING → SUCCEEDED / INFEASIBLE), ``TrialSuggestion``,
``TrialFilter``, and ``MetadataDelta`` for metadata update RPCs.
"""

from __future__ import annotations

import collections.abc
import dataclasses
import datetime
import enum
from typing import Any, Callable, Dict, Iterable, Iterator, List, Mapping, Optional, Union

from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier.parameter_config import ParameterValueTypes

Metadata = common.Metadata


class TrialStatus(enum.Enum):
    """Trial lifecycle states."""

    UNKNOWN = "UNKNOWN"
    REQUESTED = "REQUESTED"
    ACTIVE = "ACTIVE"
    STOPPING = "STOPPING"
    COMPLETED = "COMPLETED"


@dataclasses.dataclass(frozen=True)
class Metric:
    """A single scalar result. NaN is allowed and signals a failed evaluation."""

    value: float
    std: Optional[float] = None

    def __post_init__(self):
        object.__setattr__(self, "value", float(self.value))
        if self.std is not None:
            if self.std < 0:
                raise ValueError(f"Metric std must be >= 0, got {self.std}.")
            object.__setattr__(self, "std", float(self.std))


@dataclasses.dataclass(frozen=True)
class ParameterValue:
    """A typed parameter assignment with explicit casting accessors."""

    value: ParameterValueTypes

    def __post_init__(self):
        if not isinstance(self.value, (str, int, float, bool)):
            raise TypeError(f"ParameterValue must be str/int/float/bool, got {type(self.value)}")

    def cast_as_internal(self, internal_type: Any) -> ParameterValueTypes:
        """Casts to a ParameterType's canonical python type (duck-typed)."""
        name = getattr(internal_type, "name", str(internal_type))
        if name == "DOUBLE" or name == "DISCRETE":
            return self.as_float
        if name == "INTEGER":
            return self.as_int
        if name == "CATEGORICAL":
            return self.as_str
        return self.value

    @property
    def as_float(self) -> float:
        return float(self.value)  # type: ignore[arg-type]

    @property
    def as_int(self) -> int:
        f = float(self.value)  # type: ignore[arg-type]
        if not f.is_integer():
            raise ValueError(f"Cannot cast {self.value!r} to int losslessly.")
        return int(f)

    @property
    def as_str(self) -> str:
        if isinstance(self.value, bool):
            return "True" if self.value else "False"
        return str(self.value)

    @property
    def as_bool(self) -> bool:
        if isinstance(self.value, bool):
            return self.value
        if isinstance(self.value, str):
            if self.value.lower() in ("true", "1"):
                return True
            if self.value.lower() in ("false", "0"):
                return False
            raise ValueError(f"Cannot cast {self.value!r} to bool.")
        if isinstance(self.value, (int, float)):
            if float(self.value) == 1.0:
                return True
            if float(self.value) == 0.0:
                return False
        raise ValueError(f"Cannot cast {self.value!r} to bool.")


class ParameterDict(collections.abc.MutableMapping):
    """Mapping name → ParameterValue; raw values are wrapped on insert.

    ``get_value(name)`` returns the raw python value; ``as_dict()`` returns a
    plain {name: raw value} dict.
    """

    def __init__(self, items: Optional[Mapping[str, Any]] = None, **kwargs: Any):
        self._items: Dict[str, ParameterValue] = {}
        merged = dict(items or {})
        merged.update(kwargs)
        for k, v in merged.items():
            self[k] = v

    def __setitem__(self, key: str, value: Any) -> None:
        if isinstance(value, ParameterValue):
            self._items[key] = value
        else:
            self._items[key] = ParameterValue(value)

    def __getitem__(self, key: str) -> ParameterValue:
        return self._items[key]

    def __delitem__(self, key: str) -> None:
        del self._items[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def get_value(self, key: str, default: Any = None) -> Any:
        pv = self._items.get(key)
        return default if pv is None else pv.value

    def as_dict(self) -> Dict[str, ParameterValueTypes]:
        return {k: v.value for k, v in self._items.items()}

    def __eq__(self, other: object) -> bool:
        if isinstance(other, ParameterDict):
            return self._items == other._items
        if isinstance(other, Mapping):
            try:
                return self._items == ParameterDict(other)._items
            except TypeError:
                return False
        return NotImplemented

    def __repr__(self) -> str:
        return f"ParameterDict({self.as_dict()!r})"


@dataclasses.dataclass
class Measurement:
    """Metrics observed at one evaluation point of a trial."""

    metrics: Dict[str, Metric] = dataclasses.field(default_factory=dict)
    elapsed_secs: float = 0.0
    steps: float = 0.0

    def __post_init__(self):
        clean: Dict[str, Metric] = {}
        for name, m in dict(self.metrics).items():
            if isinstance(m, Metric):
                clean[name] = m
            elif isinstance(m, (int, float)):
                clean[name] = Metric(value=float(m))
            else:
                raise TypeError(f"Metric {name!r} must be Metric or number, got {type(m)}")
        self.metrics = clean
        if self.elapsed_secs < 0:
            raise ValueError("elapsed_secs must be >= 0.")
        if self.steps < 0:
            raise ValueError("steps must be >= 0.")

    def as_float_dict(self) -> Dict[str, float]:
        """Metric name → value (reference ``Measurement.as_float_dict``)."""
        return {name: m.value for name, m in self.metrics.items()}


@dataclasses.dataclass
class TrialSuggestion:
    """A suggested point, not yet assigned a trial id by the service."""

    parameters: ParameterDict = dataclasses.field(default_factory=ParameterDict)
    metadata: Metadata = dataclasses.field(default_factory=Metadata)

    def __post_init__(self):
        if not isinstance(self.parameters, ParameterDict):
            self.parameters = ParameterDict(self.parameters)

    def to_trial(self, uid: int = 0) -> "Trial":
        return Trial(id=uid, parameters=self.parameters, metadata=self.metadata)


@dataclasses.dataclass
class Trial:
    """A (possibly running or completed) evaluation of one parameter point."""

    id: int = 0
    parameters: ParameterDict = dataclasses.field(default_factory=ParameterDict)
    metadata: Metadata = dataclasses.field(default_factory=Metadata)
    assigned_worker: Optional[str] = None
    is_requested: bool = False
    stopping_reason: Optional[str] = None
    _is_stopping: bool = dataclasses.field(default=False)
    measurements: List[Measurement] = dataclasses.field(default_factory=list)
    final_measurement: Optional[Measurement] = None
    infeasibility_reason: Optional[str] = None
    creation_time: Optional[datetime.datetime] = None
    completion_time: Optional[datetime.datetime] = None

    def __post_init__(self):
        if not isinstance(self.parameters, ParameterDict):
            self.parameters = ParameterDict(self.parameters)
        if self.creation_time is None:
            self.creation_time = datetime.datetime.now(datetime.timezone.utc)
        if (self.final_measurement is not None or self.infeasibility_reason is not None) and (
            self.completion_time is None
        ):
            self.completion_time = datetime.datetime.now(datetime.timezone.utc)

    # -- lifecycle --

    @property
    def is_completed(self) -> bool:
        return self.final_measurement is not None or self.infeasibility_reason is not None

    @property
    def infeasible(self) -> bool:
        return self.infeasibility_reason is not None

    @property
    def final_measurement_or_die(self) -> Measurement:
        """The final measurement, raising if the trial has none (reference
        ``Trial.final_measurement_or_die``)."""
        if self.final_measurement is None:
            raise ValueError(f"Trial {self.id} has no final measurement.")
        return self.final_measurement

    @property
    def status(self) -> TrialStatus:
        if self.is_completed:
            return TrialStatus.COMPLETED
        if self._is_stopping:
            return TrialStatus.STOPPING
        if self.is_requested:
            return TrialStatus.REQUESTED
        return TrialStatus.ACTIVE

    def complete(
        self,
        measurement: Optional[Measurement] = None,
        *,
        infeasibility_reason: Optional[str] = None,
        inplace: bool = True,
    ) -> "Trial":
        """Marks the trial completed with a final measurement.

        With neither a measurement nor an infeasibility reason, the last
        intermediate measurement is promoted; if none exists the trial is
        marked infeasible (matching the service semantics of the reference's
        ``CompleteTrial``, ``vizier_service.py:568``).
        """
        if inplace:
            target = self
        else:
            target = dataclasses.replace(
                self,
                parameters=ParameterDict(dict(self.parameters)),
                measurements=list(self.measurements),
            )
        if measurement is None and infeasibility_reason is None:
            if target.measurements:
                measurement = target.measurements[-1]
            else:
                infeasibility_reason = "Completed without any measurement."
        if measurement is not None and any(
            m.value != m.value for m in measurement.metrics.values()  # NaN check
        ):
            infeasibility_reason = infeasibility_reason or "NaN metric value."
        target.final_measurement = measurement
        target.infeasibility_reason = infeasibility_reason
        target.is_requested = False
        target._is_stopping = False
        target.completion_time = datetime.datetime.now(datetime.timezone.utc)
        return target

    def stop(self, reason: Optional[str] = None) -> None:
        if not self.is_completed:
            self._is_stopping = True
            self.stopping_reason = reason

    @property
    def duration(self) -> Optional[datetime.timedelta]:
        if self.completion_time is not None and self.creation_time is not None:
            return self.completion_time - self.creation_time
        return None

    def to_suggestion(self) -> TrialSuggestion:
        return TrialSuggestion(parameters=self.parameters, metadata=self.metadata)


@dataclasses.dataclass
class TrialFilter:
    """Predicate over trials: by ids, min id, and/or status set."""

    ids: Optional[frozenset] = None
    min_id: Optional[int] = None
    status: Optional[frozenset] = None

    def __post_init__(self):
        if self.ids is not None:
            self.ids = frozenset(self.ids)
        if self.status is not None:
            self.status = frozenset(
                s if isinstance(s, TrialStatus) else TrialStatus(s) for s in self.status
            )

    def __call__(self, trial: Trial) -> bool:
        if self.ids is not None and trial.id not in self.ids:
            return False
        if self.min_id is not None and trial.id < self.min_id:
            return False
        if self.status is not None and trial.status not in self.status:
            return False
        return True


@dataclasses.dataclass
class MetadataDelta:
    """Metadata updates addressed to a study and/or individual trials."""

    on_study: Metadata = dataclasses.field(default_factory=Metadata)
    on_trials: Dict[int, Metadata] = dataclasses.field(default_factory=dict)

    def assign(
        self,
        namespace: str,
        key: str,
        value: Any,
        *,
        trial_id: Optional[int] = None,
        trial: Optional[Trial] = None,
    ) -> None:
        if trial is not None:
            trial_id = trial.id
        if trial_id is None:
            self.on_study.abs_ns(common.Namespace(namespace))[key] = value
        else:
            md = self.on_trials.setdefault(trial_id, Metadata())
            md.abs_ns(common.Namespace(namespace))[key] = value

    @property
    def empty(self) -> bool:
        return not self.on_study.namespaces() and not any(
            md.namespaces() for md in self.on_trials.values()
        )


# Convenience containers used by Designer.update (reference:
# vizier/_src/algorithms/core/abstractions.py:31-56).
@dataclasses.dataclass(frozen=True)
class CompletedTrials:
    """Completed trials delivered to a Designer exactly once each."""

    trials: tuple

    def __init__(self, trials: Iterable[Trial] = ()):
        object.__setattr__(self, "trials", tuple(trials))
        for t in self.trials:
            if not t.is_completed:
                raise ValueError(f"Trial {t.id} is not completed.")


@dataclasses.dataclass(frozen=True)
class ActiveTrials:
    """Currently-active (pending) trials; delivered on every update."""

    trials: tuple = ()

    def __init__(self, trials: Iterable[Trial] = ()):
        object.__setattr__(self, "trials", tuple(trials))
        for t in self.trials:
            if t.status != TrialStatus.ACTIVE:
                raise ValueError(f"Trial {t.id} is not ACTIVE (status={t.status}).")
