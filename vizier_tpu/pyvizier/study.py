"""Pythia-side study types.

Parity with ``/root/reference/vizier/_src/pyvizier/pythia/study.py:25,39``:
the study lifecycle state and the lightweight descriptor handed to policies.
"""

from __future__ import annotations

import dataclasses
import enum

from vizier_tpu.pyvizier import study_config as sc


class StudyState(enum.Enum):
    ACTIVE = "ACTIVE"
    ABORTED = "ABORTED"
    COMPLETED = "COMPLETED"


@dataclasses.dataclass(frozen=True)
class StudyStateInfo:
    state: StudyState
    explanation: str = ""


@dataclasses.dataclass(frozen=True)
class StudyDescriptor:
    """What a Policy needs to know about a study to make suggestions."""

    config: sc.StudyConfig
    guid: str = ""
    max_trial_id: int = 0


@dataclasses.dataclass
class ProblemAndTrials:
    """Container pairing a problem statement with its trials.

    Parity with ``/root/reference/vizier/_src/pyvizier/shared/study.py:25``;
    the unit benchmark pipelines pass around (analyzers, state dumps).
    """

    problem: "base_study_config.ProblemStatement"  # noqa: F821 (kept unimported to avoid a cycle)
    trials: list = dataclasses.field(default_factory=list)

    def __post_init__(self):
        self.trials = list(self.trials)
