"""Public PyVizier facade: the shared data model.

Mirrors the reference facade ``/root/reference/vizier/pyvizier/__init__.py``.
"""

from vizier_tpu.pyvizier.base_study_config import (
    MetricInformation,
    MetricsConfig,
    MetricType,
    ObjectiveMetricGoal,
    ProblemStatement,
)
from vizier_tpu.pyvizier.common import Metadata, MetadataValue, Namespace
from vizier_tpu.pyvizier.parameter_config import (
    ExternalType,
    FidelityConfig,
    InvalidParameterError,
    ParameterConfig,
    ParameterType,
    ParameterValueTypes,
    ScaleType,
    SearchSpace,
    SearchSpaceSelector,
)
from vizier_tpu.pyvizier.context import Context
from vizier_tpu.pyvizier.study import (
    ProblemAndTrials,
    StudyDescriptor,
    StudyState,
    StudyStateInfo,
)
from vizier_tpu.pyvizier.study_config import (
    Algorithm,
    AutomatedStoppingConfig,
    ObservationNoise,
    StudyConfig,
)
from vizier_tpu.pyvizier.trial import (
    ActiveTrials,
    CompletedTrials,
    Measurement,
    MetadataDelta,
    Metric,
    ParameterDict,
    ParameterValue,
    Trial,
    TrialFilter,
    TrialStatus,
    TrialSuggestion,
)

__all__ = [
    "ActiveTrials",
    "Algorithm",
    "AutomatedStoppingConfig",
    "CompletedTrials",
    "ExternalType",
    "FidelityConfig",
    "InvalidParameterError",
    "Measurement",
    "Metadata",
    "MetadataDelta",
    "MetadataValue",
    "Metric",
    "MetricInformation",
    "MetricType",
    "MetricsConfig",
    "Namespace",
    "ObjectiveMetricGoal",
    "ObservationNoise",
    "ParameterConfig",
    "ParameterDict",
    "ParameterType",
    "ParameterValue",
    "ParameterValueTypes",
    "ProblemStatement",
    "ScaleType",
    "SearchSpace",
    "SearchSpaceSelector",
    "StudyConfig",
    "StudyDescriptor",
    "StudyState",
    "StudyStateInfo",
    "Trial",
    "TrialFilter",
    "TrialStatus",
    "TrialSuggestion",
]
