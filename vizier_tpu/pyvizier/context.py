"""Context: side information attached to a study (e.g. contextual bandits).

Parity with ``/root/reference/vizier/_src/pyvizier/shared/context.py:29``:
a description, a parameter assignment for the context variables, metadata,
and related links.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class Context:
    """Side-channel parameter assignment plus metadata for a study."""

    description: Optional[str] = None
    parameters: Dict[str, trial_.ParameterValue] = dataclasses.field(
        default_factory=dict
    )
    metadata: common.Metadata = dataclasses.field(default_factory=common.Metadata)
    related_links: Dict[str, str] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.description is not None and not isinstance(self.description, str):
            raise TypeError(f"description must be str, got {self.description!r}")
        for k, v in self.parameters.items():
            if not isinstance(k, str):
                raise TypeError(f"parameter keys must be str, got {k!r}")
            if not isinstance(v, trial_.ParameterValue):
                raise TypeError(
                    f"parameter values must be ParameterValue, got {v!r}"
                )
        for k, v in self.related_links.items():
            if not isinstance(k, str) or not isinstance(v, str):
                raise TypeError(f"related_links must be str->str, got {k!r}: {v!r}")
