"""Sequential conditional-tree traversal.

Parity with
``/root/reference/vizier/_src/pyvizier/shared/parameter_iterators.py:29``
(``SequentialParameterBuilder``): walks the conditional parameter tree,
yielding each *active* config for the caller to choose a value; chosen
values determine which children become active.
"""

from __future__ import annotations

from typing import Generator, Optional

from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_

_SENTINEL = object()


class SequentialParameterBuilder:
    """Generator protocol: iterate configs, send back chosen values.

    Example::

        builder = SequentialParameterBuilder(space)
        for config in builder:
            builder.choose_value(my_value_for(config))
        parameters = builder.parameters
    """

    def __init__(self, search_space: pc.SearchSpace):
        self._parameters = trial_.ParameterDict()
        self._gen = self._walk(search_space)
        self._current: Optional[pc.ParameterConfig] = None
        self._pending = _SENTINEL  # config produced by the last send()
        self._exhausted = False

    def _walk(
        self, space: pc.SearchSpace
    ) -> Generator[pc.ParameterConfig, pc.ParameterValueTypes, None]:
        def visit(config: pc.ParameterConfig):
            value = yield config
            self._parameters[config.name] = config.cast_value(value)
            for child in config.children:
                if any(
                    pc.parent_value_matches(value, pv)
                    for pv in child.matching_parent_values
                ):
                    yield from visit(child)

        for top in space.parameters:
            yield from visit(top)

    def __iter__(self) -> "SequentialParameterBuilder":
        return self

    def __next__(self) -> pc.ParameterConfig:
        if self._current is not None:
            raise RuntimeError("choose_value() must be called before advancing.")
        if self._exhausted:
            raise StopIteration
        if self._pending is not _SENTINEL:
            self._current = self._pending  # type: ignore[assignment]
            self._pending = _SENTINEL
        else:
            self._current = next(self._gen)
        return self._current

    def choose_value(self, value: pc.ParameterValueTypes) -> None:
        if self._current is None:
            raise RuntimeError("No pending parameter; call next() first.")
        self._current = None
        try:
            # send() delivers the value and advances to the next yield.
            self._pending = self._gen.send(value)
        except StopIteration:
            self._exhausted = True

    @property
    def parameters(self) -> trial_.ParameterDict:
        return self._parameters
