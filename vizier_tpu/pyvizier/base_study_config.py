"""Problem statements: metric configuration + search space.

Functional parity with the reference's
``/root/reference/vizier/_src/pyvizier/shared/base_study_config.py:55,92,222,306``:
``MetricInformation`` (goal, optional safety config, optional value range),
``MetricsConfig`` (an ordered collection with single/multi-objective
predicates), and ``ProblemStatement`` binding a search space, metrics, and
study metadata.
"""

from __future__ import annotations

import collections.abc
import dataclasses
import enum
import math
from typing import Any, Callable, Iterable, Iterator, List, Optional, Union

from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier import parameter_config as pc


class ObjectiveMetricGoal(enum.Enum):
    MAXIMIZE = "MAXIMIZE"
    MINIMIZE = "MINIMIZE"

    @property
    def is_maximize(self) -> bool:
        return self == ObjectiveMetricGoal.MAXIMIZE

    @property
    def is_minimize(self) -> bool:
        return self == ObjectiveMetricGoal.MINIMIZE


class MetricType(str, enum.Enum):
    """OBJECTIVE (optimized) vs SAFETY (soft constraint) — reference
    ``base_study_config.py:71``. str-valued so ``m.type == "SAFETY"``
    comparisons keep working."""

    OBJECTIVE = "OBJECTIVE"
    SAFETY = "SAFETY"

    # Keep str()/f-string output identical to the plain strings the old
    # `type` property returned ("OBJECTIVE", not "MetricType.OBJECTIVE").
    __str__ = str.__str__
    __format__ = str.__format__

    @property
    def is_safety(self) -> bool:
        return self == MetricType.SAFETY

    @property
    def is_objective(self) -> bool:
        return self == MetricType.OBJECTIVE


@dataclasses.dataclass(frozen=True)
class MetricInformation:
    """Configuration of one reported metric.

    A metric with ``safety_threshold`` set is a *safety* metric (constraint),
    not an objective: trials violating the threshold are unsafe.
    """

    name: str = ""
    goal: ObjectiveMetricGoal = ObjectiveMetricGoal.MAXIMIZE
    safety_threshold: Optional[float] = None
    desired_min_safe_trials_fraction: Optional[float] = None
    min_value: float = -math.inf
    max_value: float = math.inf

    def __post_init__(self):
        if isinstance(self.goal, str):
            object.__setattr__(self, "goal", ObjectiveMetricGoal(self.goal))
        if self.min_value > self.max_value:
            raise ValueError(
                f"{self.name}: min_value {self.min_value} > max_value {self.max_value}"
            )
        frac = self.desired_min_safe_trials_fraction
        if frac is not None and not (0.0 <= frac <= 1.0):
            raise ValueError(f"{self.name}: safe-trials fraction must be in [0,1], got {frac}")

    @property
    def type(self) -> MetricType:
        return (
            MetricType.SAFETY
            if self.safety_threshold is not None
            else MetricType.OBJECTIVE
        )

    @property
    def is_safety_metric(self) -> bool:
        return self.safety_threshold is not None

    @property
    def range(self) -> float:
        """max_value - min_value; can be infinite."""
        return self.max_value - self.min_value

    def min_value_or(self, default_fn: Callable[[], float] = lambda: -math.inf) -> float:
        return self.min_value if math.isfinite(self.min_value) else default_fn()

    def max_value_or(self, default_fn: Callable[[], float] = lambda: math.inf) -> float:
        return self.max_value if math.isfinite(self.max_value) else default_fn()

    def flip_goal(self) -> "MetricInformation":
        new_goal = (
            ObjectiveMetricGoal.MINIMIZE if self.goal.is_maximize else ObjectiveMetricGoal.MAXIMIZE
        )
        return dataclasses.replace(self, goal=new_goal)


class MetricsConfig(collections.abc.Collection):
    """Ordered, name-unique collection of MetricInformation."""

    def __init__(self, metrics: Iterable[MetricInformation] = ()):
        self._metrics: List[MetricInformation] = list(metrics)
        names = [m.name for m in self._metrics]
        if len(set(names)) != len(names):
            raise ValueError(f"Duplicate metric names: {names}")

    def append(self, metric: MetricInformation) -> None:
        if any(m.name == metric.name for m in self._metrics):
            raise ValueError(f"Metric {metric.name!r} already present.")
        self._metrics.append(metric)

    def extend(self, metrics: Iterable[MetricInformation]) -> None:
        for m in metrics:
            self.append(m)

    def __iter__(self) -> Iterator[MetricInformation]:
        return iter(self._metrics)

    def __len__(self) -> int:
        return len(self._metrics)

    def __contains__(self, item: object) -> bool:
        return item in self._metrics

    def __getitem__(self, index: int) -> MetricInformation:
        return self._metrics[index]

    def get(self, name: str) -> MetricInformation:
        for m in self._metrics:
            if m.name == name:
                return m
        raise KeyError(f"No metric named {name!r}.")

    @staticmethod
    def _type_set(
        types: Union[str, MetricType, Iterable[Union[str, MetricType]]]
    ) -> set:
        if isinstance(types, (str, MetricType)):
            types = (types,)
        return {MetricType(t) for t in types}

    def of_type(
        self, include: Union[str, MetricType, Iterable[Union[str, MetricType]]]
    ) -> "MetricsConfig":
        wanted = self._type_set(include)
        return MetricsConfig(m for m in self._metrics if m.type in wanted)

    def exclude_type(
        self, exclude: Union[str, MetricType, Iterable[Union[str, MetricType]]]
    ) -> "MetricsConfig":
        unwanted = self._type_set(exclude)
        return MetricsConfig(m for m in self._metrics if m.type not in unwanted)

    def item(self) -> MetricInformation:
        """The unique objective metric; raises unless single-objective."""
        objectives = [m for m in self._metrics if not m.is_safety_metric]
        if len(objectives) != 1:
            raise ValueError(f"Expected exactly one objective metric, have {len(objectives)}.")
        return objectives[0]

    @property
    def is_single_objective(self) -> bool:
        return sum(1 for m in self._metrics if not m.is_safety_metric) == 1

    @property
    def is_safety_metric_present(self) -> bool:
        return any(m.is_safety_metric for m in self._metrics)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, MetricsConfig):
            return NotImplemented
        return self._metrics == other._metrics

    def __repr__(self) -> str:
        return f"MetricsConfig({self._metrics!r})"


@dataclasses.dataclass
class ProblemStatement:
    """Search space + metric configuration + study-level metadata."""

    search_space: pc.SearchSpace = dataclasses.field(default_factory=pc.SearchSpace)
    metric_information: MetricsConfig = dataclasses.field(default_factory=MetricsConfig)
    metadata: common.Metadata = dataclasses.field(default_factory=common.Metadata)

    def __post_init__(self):
        if not isinstance(self.metric_information, MetricsConfig):
            self.metric_information = MetricsConfig(self.metric_information)

    @property
    def is_single_objective(self) -> bool:
        return self.metric_information.is_single_objective

    @property
    def single_objective_metric_name(self) -> Optional[str]:
        if self.is_single_objective:
            return self.metric_information.item().name
        return None

    @property
    def is_safety_metric_present(self) -> bool:
        return self.metric_information.is_safety_metric_present

    def to_problem(self) -> "ProblemStatement":
        return self

    def __repr__(self) -> str:
        return (
            f"ProblemStatement(search_space={self.search_space!r}, "
            f"metric_information={self.metric_information!r})"
        )
