"""Multimetric utilities: Pareto optimality, hypervolume, safety checking.

Parity with ``/root/reference/vizier/_src/pyvizier/multimetric/``
(``pareto_optimal.py:24,87``, ``hypervolume.py:68``, ``safety.py:24``) —
thin numpy-facing wrappers over the XLA ops in ``vizier_tpu.ops.pareto``
(the TPU build runs the algorithms on device instead of the reference's
O(n²) numpy loops).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from vizier_tpu.ops import pareto as pareto_ops
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


class ParetoOptimalAlgorithm:
    """Frontier membership / Pareto rank over [N, M] MAXIMIZE matrices."""

    def is_pareto_optimal(self, points: np.ndarray) -> np.ndarray:
        points = np.asarray(points, dtype=np.float32)
        if points.size == 0:
            return np.zeros((0,), dtype=bool)
        return np.asarray(pareto_ops.is_frontier(points))

    def pareto_rank(self, points: np.ndarray) -> np.ndarray:
        points = np.asarray(points, dtype=np.float32)
        if points.size == 0:
            return np.zeros((0,), dtype=np.int32)
        return np.asarray(pareto_ops.pareto_rank(points))


# Reference exposes a naive and a fast variant; both map to the XLA op here.
FastParetoOptimalAlgorithm = ParetoOptimalAlgorithm
NaiveParetoOptimalAlgorithm = ParetoOptimalAlgorithm


class ParetoFrontier:
    """Hypervolume of a frontier w.r.t. an origin (random-direction MC)."""

    def __init__(
        self,
        points: np.ndarray,
        origin: Optional[np.ndarray] = None,
        *,
        num_vectors: int = 10_000,
        seed: int = 0,
    ):
        self._points = np.asarray(points, dtype=np.float32)
        self._origin = (
            np.asarray(origin, dtype=np.float32)
            if origin is not None
            else np.zeros(self._points.shape[-1], dtype=np.float32)
        )
        self._num_vectors = num_vectors
        self._rng = jax.random.PRNGKey(seed)

    def hypervolume(self, is_cumulative: bool = False) -> np.ndarray:
        shifted = np.maximum(self._points - self._origin[None, :], 0.0)
        cum = pareto_ops.cum_hypervolume_origin(
            shifted.astype(np.float32), self._rng, num_vectors=self._num_vectors
        )
        return np.asarray(cum) if is_cumulative else float(np.asarray(cum)[-1])


class SafetyChecker:
    """Filters trials violating safety-metric thresholds."""

    def __init__(self, metrics: base_study_config.MetricsConfig):
        self._safety = [m for m in metrics if m.is_safety_metric]

    def warp_unsafe_trials(
        self, trials: Sequence[trial_.Trial]
    ) -> Sequence[trial_.Trial]:
        """Marks unsafe completed trials infeasible (in place); returns them.

        Measurement data is preserved (so safety checks and analyzers keep
        working); label encoders exclude infeasible trials regardless of
        their measurements, so the objective cannot leak into model training.
        """
        for t in trials:
            if not self.is_safe(t):
                t.infeasibility_reason = t.infeasibility_reason or "Safety violation."
        return trials

    def is_safe(self, trial: trial_.Trial) -> bool:
        if trial.final_measurement is None:
            return True
        for info in self._safety:
            metric = trial.final_measurement.metrics.get(info.name)
            if metric is None:
                continue
            threshold = info.safety_threshold or 0.0
            if info.goal.is_maximize and metric.value < threshold:
                return False
            if info.goal.is_minimize and metric.value > threshold:
                return False
        return True
