"""StudyConfig: a ProblemStatement plus service-level algorithm settings.

Functional parity with the reference's OSS StudyConfig
(``/root/reference/vizier/_src/pyvizier/oss/study_config.py:63,93,134``):
algorithm selection, observation-noise hint, automated (early) stopping
config, and an optional dedicated Pythia endpoint. Serialization for the
service layer is handled by ``vizier_tpu.service.converters`` rather than
proto classes here.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, Optional

from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


class Algorithm(str, enum.Enum):
    """Well-known algorithm names accepted by the default policy factory.

    The service accepts arbitrary strings; these are the built-ins
    (reference: ``vizier/_src/service/policy_factory.py:28-115``).
    """

    ALGORITHM_UNSPECIFIED = "ALGORITHM_UNSPECIFIED"
    DEFAULT = "DEFAULT"
    GP_UCB_PE = "GP_UCB_PE"
    GAUSSIAN_PROCESS_BANDIT = "GAUSSIAN_PROCESS_BANDIT"
    RANDOM_SEARCH = "RANDOM_SEARCH"
    QUASI_RANDOM_SEARCH = "QUASI_RANDOM_SEARCH"
    GRID_SEARCH = "GRID_SEARCH"
    SHUFFLED_GRID_SEARCH = "SHUFFLED_GRID_SEARCH"
    NSGA2 = "NSGA2"
    EAGLE_STRATEGY = "EAGLE_STRATEGY"
    CMA_ES = "CMA_ES"
    BOCS = "BOCS"
    HARMONICA = "HARMONICA"

    def __str__(self) -> str:
        return self.value


class ObservationNoise(enum.Enum):
    OBSERVATION_NOISE_UNSPECIFIED = "OBSERVATION_NOISE_UNSPECIFIED"
    LOW = "LOW"
    HIGH = "HIGH"


@dataclasses.dataclass(frozen=True)
class AutomatedStoppingConfig:
    """Early-stopping configuration attached to a study.

    ``use_steps=True`` compares trials by step count, else by elapsed secs
    (mirrors the reference's ``DefaultEarlyStoppingSpec``,
    ``oss/automated_stopping.py:46``).
    """

    use_steps: bool = True
    min_num_trials: int = 5
    # "median": median curve rule. "regression": gradient-boosted
    # final-objective prediction from partial curves (algorithms/regression).
    rule: str = "median"

    def __post_init__(self):
        if self.rule not in ("median", "regression"):
            raise ValueError(
                f"Unknown early-stopping rule {self.rule!r}; "
                "choices: 'median' | 'regression'."
            )

    @classmethod
    def default_stopping_spec(cls, *, use_steps: bool = True, min_num_trials: int = 5):
        return cls(use_steps=use_steps, min_num_trials=min_num_trials)

    @classmethod
    def regression_stopping_spec(
        cls, *, use_steps: bool = True, min_num_trials: int = 10
    ):
        return cls(
            use_steps=use_steps, min_num_trials=min_num_trials, rule="regression"
        )


@dataclasses.dataclass
class StudyConfig(base_study_config.ProblemStatement):
    """ProblemStatement + algorithm + service-level knobs."""

    algorithm: str = Algorithm.DEFAULT.value
    observation_noise: ObservationNoise = ObservationNoise.OBSERVATION_NOISE_UNSPECIFIED
    automated_stopping_config: Optional[AutomatedStoppingConfig] = None
    pythia_endpoint: Optional[str] = None

    def __post_init__(self):
        super().__post_init__()
        if isinstance(self.algorithm, Algorithm):
            self.algorithm = self.algorithm.value

    @classmethod
    def from_problem(
        cls, problem: base_study_config.ProblemStatement, algorithm: str = Algorithm.DEFAULT.value
    ) -> "StudyConfig":
        return cls(
            search_space=problem.search_space,
            metric_information=problem.metric_information,
            metadata=problem.metadata,
            algorithm=str(algorithm),
        )

    def to_problem(self) -> base_study_config.ProblemStatement:
        return base_study_config.ProblemStatement(
            search_space=self.search_space,
            metric_information=self.metric_information,
            metadata=self.metadata,
        )

    # -- user-facing value mapping ----------------------------------------

    def trial_parameters(self, trial: trial_.Trial) -> Dict[str, Any]:
        """Trial parameters mapped through each config's external type.

        E.g. a bool parameter (stored as CATEGORICAL 'True'/'False') comes
        back as a python bool; an INTEGER-external DISCRETE comes back as int.
        """
        out: Dict[str, Any] = {}
        for name, pv in trial.parameters.items():
            try:
                config = self.search_space.get(name)
            except KeyError:
                out[name] = pv.value
                continue
            ext = config.external_type
            if ext == pc.ExternalType.BOOLEAN:
                out[name] = pv.as_bool
            elif ext == pc.ExternalType.INTEGER:
                out[name] = pv.as_int
            elif ext == pc.ExternalType.FLOAT:
                out[name] = pv.as_float
            else:
                out[name] = pv.cast_as_internal(config.type)
        return out
