"""Namespaced metadata store shared by studies and trials.

Functional parity with the reference's ``Namespace``/``Metadata``
(``/root/reference/vizier/_src/pyvizier/shared/common.py:90,225``), rebuilt
from scratch: a hierarchical namespace (tuple of string components, with a
``:``-separated escaped text encoding) mapping to per-namespace ``key ->
value`` stores, where values are ``str``, ``float``/``int``, ``bytes``, or
protobuf messages (anything exposing ``SerializeToString``).

Algorithm state checkpointing rides on this store (designers serialize their
state into a study-scoped namespace), so round-trip fidelity of the encoding
matters; see the property tests in ``tests/pyvizier/test_common.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Mapping, MutableMapping, Optional, Tuple, Union

# Metadata values: plain scalars/bytes, or any protobuf-like object.
MetadataValue = Union[str, float, int, bytes, Any]

_ESCAPE = "\\"
_SEP = ":"


def _escape_component(component: str) -> str:
    return component.replace(_ESCAPE, _ESCAPE + _ESCAPE).replace(_SEP, _ESCAPE + _SEP)


def _split_encoded(encoded: str) -> List[str]:
    """Splits on unescaped separators and unescapes each component."""
    components: List[str] = []
    current: List[str] = []
    it = iter(encoded)
    for ch in it:
        if ch == _ESCAPE:
            nxt = next(it, None)
            if nxt is None:
                current.append(_ESCAPE)
            else:
                current.append(nxt)
        elif ch == _SEP:
            components.append("".join(current))
            current = []
        else:
            current.append(ch)
    components.append("".join(current))
    return components


class Namespace(tuple):
    """An immutable hierarchical namespace: a tuple of string components.

    The canonical text encoding prefixes each component with ``:`` and
    escapes literal ``:`` and ``\\`` inside components, so encoding is
    injective and ``Namespace.decode`` is its exact inverse. The root
    namespace encodes to the empty string.
    """

    __slots__ = ()

    def __new__(cls, components: Union[str, Iterable[str]] = ()) -> "Namespace":
        if isinstance(components, str):
            # A convenience: treat a plain string as a single component unless
            # it starts with ':' (then it is a canonical encoding).
            if components.startswith(_SEP):
                return cls.decode(components)
            components = (components,) if components else ()
        comps = tuple(components)
        for c in comps:
            if not isinstance(c, str):
                raise TypeError(f"Namespace components must be str, got {type(c)}")
        return super().__new__(cls, comps)

    @classmethod
    def decode(cls, encoded: str) -> "Namespace":
        """Inverse of ``encode``; also accepts non-canonical bare strings."""
        if not encoded:
            return cls(())
        if encoded.startswith(_SEP):
            encoded = encoded[1:]
        return super().__new__(cls, tuple(_split_encoded(encoded)))

    def encode(self) -> str:
        return "".join(_SEP + _escape_component(c) for c in self)

    def __add__(self, other: Iterable[str]) -> "Namespace":  # type: ignore[override]
        return Namespace(tuple(self) + tuple(Namespace(other)))

    def startswith(self, prefix: Iterable[str]) -> bool:
        p = tuple(Namespace(prefix))
        return tuple(self[: len(p)]) == p

    def ancestors(self) -> Iterator["Namespace"]:
        """Yields root, then each successively deeper prefix, ending with self."""
        for i in range(len(self) + 1):
            yield Namespace(self[:i])

    def __repr__(self) -> str:
        return f"Namespace({self.encode()!r})"


class _NamespaceView(MutableMapping[str, MetadataValue]):
    """A mutable dict-like view of one namespace inside a Metadata."""

    def __init__(self, metadata: "Metadata", ns: Namespace):
        self._metadata = metadata
        self._ns = ns

    def _store(self) -> Dict[str, MetadataValue]:
        return self._metadata._stores.setdefault(self._ns, {})

    def __getitem__(self, key: str) -> MetadataValue:
        return self._metadata._stores.get(self._ns, {})[key]

    def __setitem__(self, key: str, value: MetadataValue) -> None:
        self._store()[key] = value

    def __delitem__(self, key: str) -> None:
        del self._metadata._stores.get(self._ns, {})[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._metadata._stores.get(self._ns, {}))

    def __len__(self) -> int:
        return len(self._metadata._stores.get(self._ns, {}))

    def __contains__(self, key: object) -> bool:
        return key in self._metadata._stores.get(self._ns, {})

    def get(
        self, key: str, default: Any = None, *, cls: Optional[type] = None
    ) -> Any:
        """The value for ``key``, or ``default`` if absent or unconvertible.

        Bare ``get(key)`` returns whatever was stored (str, float, bytes,
        proto — unchanged). Passing ``cls`` requests typed access (reference
        ``Metadata.get`` contract): values already of type ``cls`` pass
        through, packed ``Any`` protos unpack into a ``cls()`` message, and
        anything else converts via ``cls(value)`` — e.g.
        ``get('restarts', cls=int)`` parses a stored ``"4"``.
        """
        store = self._metadata._stores.get(self._ns, {})
        if key not in store:
            return default
        try:
            return self._coerce(store[key], cls)
        except (TypeError, ValueError):
            return default

    @staticmethod
    def _coerce(value: MetadataValue, cls: Optional[type]) -> Any:
        if cls is None or isinstance(value, cls):
            return value
        if hasattr(value, "Unpack"):  # packed protobuf Any
            if not hasattr(cls, "DESCRIPTOR"):
                raise TypeError(f"Cannot unpack Any proto to non-proto {cls}.")
            message = cls()
            if not value.Unpack(message):
                raise TypeError(f"Cannot unpack Any proto to {cls}.")
            return message
        return cls(value)

    def get_or_error(self, key: str, *, cls: Optional[type] = None) -> Any:
        """Like ``[]``, with optional ``cls`` coercion; KeyError when absent
        (reference ``Metadata.get_or_error``)."""
        return self._coerce(self._metadata._stores.get(self._ns, {})[key], cls)

    def items_by_cls(self, *, cls: type) -> Iterator[Tuple[str, Any]]:
        """(key, value) pairs in this namespace whose value is a ``cls``."""
        for key, value in self._metadata._stores.get(self._ns, {}).items():
            if isinstance(value, cls):
                yield key, value

    def update(self, *args, **kwargs) -> None:
        self._store().update(*args, **kwargs)

    def ns(self, component: str) -> "_NamespaceView":
        return _NamespaceView(self._metadata, self._ns + (component,))

    @property
    def namespace(self) -> Namespace:
        return self._ns

    def current_ns(self) -> Namespace:  # reference-compat alias
        return self._ns


class Metadata(_NamespaceView):
    """Namespaced key→value store.

    ``Metadata()`` views the root namespace. ``m.ns('a').ns('b')['k'] = v``
    writes key ``k`` in namespace ``(a, b)``. ``abs_ns`` jumps to an absolute
    namespace. Iteration/getitem on a view only sees that namespace's keys.
    """

    # One shared root-namespace instance: Metadata() construction sits on
    # every trial proto conversion of the serving hot path, and Namespace
    # is immutable, so all roots can be the same tuple.
    _ROOT_NS = Namespace(())

    def __init__(
        self,
        *args,
        **kwargs,
    ):
        self._stores: Dict[Namespace, Dict[str, MetadataValue]] = {}
        # Inlined _NamespaceView.__init__(self, self, _ROOT_NS) — measured
        # on the suggest hot path (4 Metadata per served trial).
        self._metadata = self
        self._ns = Metadata._ROOT_NS
        if args or kwargs:
            self.update(*args, **kwargs)

    def abs_ns(self, ns: Union[Namespace, Iterable[str], None] = None) -> _NamespaceView:
        if ns is None:
            return _NamespaceView(self, Namespace(()))
        return _NamespaceView(self, Namespace(ns))

    def namespaces(self) -> List[Namespace]:
        """All namespaces that currently hold at least one key."""
        return [ns for ns, store in self._stores.items() if store]

    def subnamespaces(self, prefix: Union[Namespace, Iterable[str]]) -> List[Namespace]:
        p = Namespace(prefix)
        return [ns for ns in self.namespaces() if ns.startswith(p)]

    def attach(self, other: "Metadata") -> None:
        """Merges ``other`` into self (other's values win on key conflicts)."""
        for ns, store in other._stores.items():
            if store:
                self._stores.setdefault(ns, {}).update(store)

    def all_items(self) -> Iterator[Tuple[Namespace, str, MetadataValue]]:
        for ns, store in self._stores.items():
            for k, v in store.items():
                yield ns, k, v

    def get_proto(self, key: str, *, cls: type) -> Optional[Any]:
        """Returns the value for ``key`` parsed as proto message ``cls``.

        Accepts values stored either as a message instance or as serialized
        bytes. Returns None if the key is missing.
        """
        value = self.get(key)
        if value is None:
            return None
        if isinstance(value, cls):
            return value
        if isinstance(value, bytes):
            msg = cls()
            msg.ParseFromString(value)
            return msg
        raise TypeError(f"Metadata key {key!r} holds {type(value)}, not {cls}")

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Metadata):
            return NotImplemented
        mine = {ns: s for ns, s in self._stores.items() if s}
        theirs = {ns: s for ns, s in other._stores.items() if s}
        return mine == theirs

    def __ne__(self, other: object) -> bool:
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq

    def __repr__(self) -> str:
        parts = [f"{ns.encode() or '(root)'}:{dict(store)}" for ns, store in self._stores.items() if store]
        return f"Metadata({', '.join(parts)})"
