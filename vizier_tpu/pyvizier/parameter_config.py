"""Search-space parameter configuration.

Functional parity with the reference's ``ParameterConfig``/``SearchSpace``
(``/root/reference/vizier/_src/pyvizier/shared/parameter_config.py:168,1298``),
designed from scratch: typed parameters (DOUBLE/INTEGER/DISCRETE/CATEGORICAL,
plus CUSTOM), scale types (LINEAR/LOG/REVERSE_LOG/UNIFORM_DISCRETE), external
types (BOOLEAN/INTEGER/FLOAT round-tripping), conditional child parameters
keyed on matching parent values, fluent builders, and traversal/continuify
utilities used by the converters.

The conditional tree is represented directly: each ``ParameterConfig`` owns a
tuple of child configs, and every child records the parent values that
activate it. A parameter is *active* in a trial iff every ancestor's assigned
value matches the child's activation set — see ``SearchSpace.is_active_path``.
"""

from __future__ import annotations

import copy
import dataclasses
import enum
import math
import re
import warnings
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

ParameterValueTypes = Union[str, int, float, bool]


class ParameterType(enum.Enum):
    DOUBLE = "DOUBLE"
    INTEGER = "INTEGER"
    CATEGORICAL = "CATEGORICAL"
    DISCRETE = "DISCRETE"
    CUSTOM = "CUSTOM"

    def is_numeric(self) -> bool:
        return self in (ParameterType.DOUBLE, ParameterType.INTEGER, ParameterType.DISCRETE)

    def is_continuous(self) -> bool:
        return self == ParameterType.DOUBLE


class ScaleType(enum.Enum):
    """How a numeric parameter is mapped to [0, 1] for modeling."""

    LINEAR = "LINEAR"
    LOG = "LOG"
    REVERSE_LOG = "REVERSE_LOG"
    UNIFORM_DISCRETE = "UNIFORM_DISCRETE"

    def is_nonlinear(self) -> bool:
        return self in (ScaleType.LOG, ScaleType.REVERSE_LOG)


class ExternalType(enum.Enum):
    """The user-facing python type a parameter value converts back to."""

    INTERNAL = "INTERNAL"
    BOOLEAN = "BOOLEAN"
    INTEGER = "INTEGER"
    FLOAT = "FLOAT"


@dataclasses.dataclass(frozen=True)
class FidelityConfig:
    """Marks a parameter as a fidelity/resource axis (multi-fidelity BO)."""

    class Mode(enum.Enum):
        SEQUENTIAL = "SEQUENTIAL"
        NESTED = "NESTED"

    mode: Mode = Mode.SEQUENTIAL


def _is_close(a: float, b: float) -> bool:
    return math.isclose(a, b, rel_tol=1e-9, abs_tol=1e-12)


@dataclasses.dataclass(frozen=True)
class ParameterConfig:
    """Immutable configuration of a single (possibly conditional) parameter.

    Use the ``factory`` classmethod (or ``SearchSpace`` fluent builders)
    rather than the raw constructor; the factory validates bounds/values and
    infers sensible scale types.
    """

    name: str
    type: ParameterType
    # For DOUBLE / INTEGER: inclusive (min, max).
    _bounds: Optional[Tuple[float, float]] = None
    # For DISCRETE (sorted floats) / CATEGORICAL (strings).
    _feasible_values: Tuple[ParameterValueTypes, ...] = ()
    scale_type: Optional[ScaleType] = None
    default_value: Optional[ParameterValueTypes] = None
    external_type: ExternalType = ExternalType.INTERNAL
    fidelity_config: Optional[FidelityConfig] = None
    # Conditional children; each child's matching_parent_values says which of
    # *this* config's values activate it.
    children: Tuple["ParameterConfig", ...] = ()
    matching_parent_values: Tuple[ParameterValueTypes, ...] = ()

    # --- construction -----------------------------------------------------

    @classmethod
    def factory(
        cls,
        name: str,
        *,
        bounds: Optional[Tuple[float, float]] = None,
        feasible_values: Optional[Sequence[ParameterValueTypes]] = None,
        scale_type: Optional[ScaleType] = None,
        default_value: Optional[ParameterValueTypes] = None,
        external_type: ExternalType = ExternalType.INTERNAL,
        fidelity_config: Optional[FidelityConfig] = None,
        children: Sequence[Tuple[Sequence[ParameterValueTypes], "ParameterConfig"]] = (),
    ) -> "ParameterConfig":
        if not name:
            raise ValueError("Parameter name must be non-empty.")
        if bounds is not None and feasible_values is not None:
            raise ValueError(
                f"{name}: at most one of bounds / feasible_values may be given "
                f"(bounds={bounds}, feasible_values={feasible_values})."
            )
        if bounds is None and feasible_values is None:
            # Neither ⇒ CUSTOM: an opaque parameter (reference
            # `parameter_config.py:255` factory semantics). Suggestion
            # algorithms and encoders REJECT spaces containing it (as in the
            # reference); it exists for externally-assigned values carried
            # verbatim through trials.
            if children:
                raise ValueError(f"{name}: CUSTOM parameters cannot have children.")
            return cls(
                name=name,
                type=ParameterType.CUSTOM,
                default_value=default_value,
                external_type=external_type,
                fidelity_config=fidelity_config,
            )
        if bounds is not None:
            lo, hi = bounds
            if isinstance(lo, bool) or isinstance(hi, bool):
                raise ValueError(f"{name}: bounds must be numeric, got bools.")
            if not (isinstance(lo, (int, float)) and isinstance(hi, (int, float))):
                raise ValueError(f"{name}: bounds must be numeric, got {bounds!r}.")
            if lo > hi:
                raise ValueError(f"{name}: min bound {lo} > max bound {hi}.")
            if isinstance(lo, int) and isinstance(hi, int):
                ptype = ParameterType.INTEGER
            else:
                ptype = ParameterType.DOUBLE
                lo, hi = float(lo), float(hi)
            cfg_bounds: Optional[Tuple[float, float]] = (lo, hi)
            values: Tuple[ParameterValueTypes, ...] = ()
        else:
            assert feasible_values is not None
            if not feasible_values:
                raise ValueError(f"{name}: feasible_values must be non-empty.")
            if len(set(feasible_values)) != len(feasible_values):
                raise ValueError(f"{name}: duplicate feasible values {feasible_values!r}.")
            if all(isinstance(v, str) for v in feasible_values):
                ptype = ParameterType.CATEGORICAL
                values = tuple(sorted(feasible_values))  # type: ignore[arg-type]
            elif all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in feasible_values):
                ptype = ParameterType.DISCRETE
                values = tuple(sorted(float(v) for v in feasible_values))
            else:
                raise ValueError(
                    f"{name}: feasible_values must be all-str (categorical) or "
                    f"all-numeric (discrete); got {feasible_values!r}."
                )
            cfg_bounds = None
        if scale_type in (ScaleType.LOG, ScaleType.REVERSE_LOG):
            if cfg_bounds is not None and cfg_bounds[0] <= 0:
                raise ValueError(
                    f"{name}: {scale_type.value} scale requires positive bounds, got {cfg_bounds}."
                )
            if ptype == ParameterType.DISCRETE and any(float(v) <= 0 for v in values):  # type: ignore[arg-type]
                raise ValueError(
                    f"{name}: {scale_type.value} scale requires positive values, got {values}."
                )
        child_tuple = tuple(
            dataclasses.replace(child, matching_parent_values=tuple(parent_values))
            for parent_values, child in children
        )
        config = cls(
            name=name,
            type=ptype,
            _bounds=cfg_bounds,
            _feasible_values=values,
            scale_type=scale_type,
            default_value=default_value,
            external_type=external_type,
            fidelity_config=fidelity_config,
            children=child_tuple,
        )
        if default_value is not None and not config.contains(default_value):
            raise ValueError(f"{name}: default {default_value!r} not in the feasible set.")
        for child in child_tuple:
            for pv in child.matching_parent_values:
                if not config.contains(pv):
                    raise ValueError(
                        f"{name}: child {child.name!r} activates on {pv!r}, "
                        "which is not a feasible parent value."
                    )
        return config

    # --- basic accessors --------------------------------------------------

    @property
    def bounds(self) -> Tuple[float, float]:
        """(min, max) for numeric types; DISCRETE returns (min, max) of values."""
        if self._bounds is not None:
            return self._bounds
        if self.type == ParameterType.DISCRETE:
            vals = [float(v) for v in self._feasible_values]  # type: ignore[arg-type]
            return (min(vals), max(vals))
        raise ValueError(f"{self.name}: bounds undefined for {self.type}.")

    @property
    def feasible_values(self) -> List[ParameterValueTypes]:
        if self._feasible_values:
            return list(self._feasible_values)
        if self.type == ParameterType.INTEGER:
            lo, hi = self._bounds  # type: ignore[misc]
            return list(range(int(lo), int(hi) + 1))
        raise ValueError(f"{self.name}: feasible_values undefined for {self.type}.")

    @property
    def num_feasible_values(self) -> float:
        if self.type == ParameterType.CUSTOM:
            return float("inf")
        if self.type == ParameterType.DOUBLE:
            lo, hi = self._bounds  # type: ignore[misc]
            return 1.0 if _is_close(lo, hi) else float("inf")
        if self.type == ParameterType.INTEGER:
            lo, hi = self._bounds  # type: ignore[misc]
            return int(hi) - int(lo) + 1
        return len(self._feasible_values)

    def contains(self, value: ParameterValueTypes) -> bool:
        """Whether ``value`` is feasible for this parameter."""
        if self.type == ParameterType.DOUBLE:
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                return False
            lo, hi = self._bounds  # type: ignore[misc]
            return lo - 1e-12 <= float(value) <= hi + 1e-12
        if self.type == ParameterType.INTEGER:
            if isinstance(value, bool):
                return False
            if isinstance(value, float) and not value.is_integer():
                return False
            if not isinstance(value, (int, float)):
                return False
            lo, hi = self._bounds  # type: ignore[misc]
            return lo <= int(value) <= hi
        if self.type == ParameterType.DISCRETE:
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                return False
            return any(_is_close(float(value), float(v)) for v in self._feasible_values)  # type: ignore[arg-type]
        if self.type == ParameterType.CATEGORICAL:
            if isinstance(value, bool) and self.external_type == ExternalType.BOOLEAN:
                value = "True" if value else "False"
            return isinstance(value, str) and value in self._feasible_values
        return True  # CUSTOM accepts anything.

    # --- transforms -------------------------------------------------------

    def continuify(self) -> "ParameterConfig":
        """Relaxes numeric/discrete parameters to DOUBLE over their range."""
        if self.children:
            raise ValueError(
                f"Cannot continuify parent parameter {self.name!r}: conditional "
                "children would be silently discarded."
            )
        if self.type == ParameterType.DOUBLE:
            return self
        if not self.type.is_numeric():
            raise ValueError(f"Cannot continuify {self.type} parameter {self.name}.")
        lo, hi = self.bounds
        scale = self.scale_type
        if scale == ScaleType.UNIFORM_DISCRETE:
            scale = ScaleType.LINEAR
        default = self.default_value
        if default is not None:
            default = float(default)  # type: ignore[arg-type]
        return ParameterConfig(
            name=self.name,
            type=ParameterType.DOUBLE,
            _bounds=(float(lo), float(hi)),
            scale_type=scale,
            default_value=default,
            external_type=self.external_type,
            matching_parent_values=self.matching_parent_values,
        )

    def traverse(self, show_children: bool = True) -> Iterator["ParameterConfig"]:
        """Pre-order DFS over this config and all descendants.

        ``show_children`` controls whether the yielded configs carry their
        ``children`` (reference ``traverse`` semantics); descendants are
        visited either way.
        """
        yield self if show_children else self.clone_without_children()
        for child in self.children:
            yield from child.traverse(show_children)

    def clone_without_children(self) -> "ParameterConfig":
        return dataclasses.replace(self, children=())

    @classmethod
    def merge(
        cls, one: "ParameterConfig", other: "ParameterConfig"
    ) -> "ParameterConfig":
        """Union of two childless configs of the same type.

        CATEGORICAL/DISCRETE merge to the union of feasible values;
        DOUBLE/INTEGER to the envelope of the bounds (reference
        ``parameter_config.py:540``). Used when combining search spaces
        from related studies (e.g. transfer-learning priors).
        """
        if one.children or other.children:
            raise ValueError(
                f"Cannot merge parameters with children: {one.name}, {other.name}."
            )
        if one.type != other.type:
            raise ValueError(
                f"Type conflict merging {one.name}: {one.type} vs {other.type}."
            )
        if one.scale_type != other.scale_type:
            warnings.warn(
                f"Scale type conflict merging {one.name}: keeping "
                f"{one.scale_type} over {other.scale_type}.",
                stacklevel=2,
            )
        # external_type survives only when unambiguous; defaults and fidelity
        # configs are dropped (reference merge rebuilds from values/bounds).
        external = (
            one.external_type
            if one.external_type == other.external_type
            else ExternalType.INTERNAL
        )
        if one.type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
            values = sorted(set(one.feasible_values) | set(other.feasible_values))
            return cls.factory(
                name=one.name,
                feasible_values=values,
                scale_type=one.scale_type,
                external_type=external,
            )
        if one.type in (ParameterType.INTEGER, ParameterType.DOUBLE):
            lo = min(one.bounds[0], other.bounds[0])
            hi = max(one.bounds[1], other.bounds[1])
            if one.type == ParameterType.INTEGER:
                lo, hi = int(lo), int(hi)
            return cls.factory(
                name=one.name,
                bounds=(lo, hi),
                scale_type=one.scale_type,
                external_type=external,
            )
        raise ValueError(f"Cannot merge {one.type} parameter {one.name}.")

    def get_subspace_deepcopy(self, value: ParameterValueTypes) -> "SearchSpace":
        """The conditional subspace active when this parameter takes ``value``.

        Returns an empty space for DOUBLE (continuous parents cannot have
        children) and validates feasibility otherwise (reference
        ``parameter_config.py:696``).
        """
        if self.type == ParameterType.DOUBLE:
            return SearchSpace()
        # Validate the RAW value before casting: cast_value truncates (e.g.
        # int(2.7) == 2), which would silently select a different subspace.
        if not self.contains(value):
            raise InvalidParameterError(
                f"{self.name}: {value!r} is not a feasible value."
            )
        value = self.cast_value(value)
        space = SearchSpace()
        space.parameters = [
            copy.deepcopy(child)
            for child in self.children
            if any(
                parent_value_matches(value, pv)
                for pv in child.matching_parent_values
            )
        ]
        return space

    def add_children(
        self, new_children: Sequence[Tuple[Sequence[ParameterValueTypes], "ParameterConfig"]]
    ) -> "ParameterConfig":
        added = tuple(
            dataclasses.replace(c, matching_parent_values=tuple(pv)) for pv, c in new_children
        )
        for child in added:
            for pv in child.matching_parent_values:
                if not self.contains(pv):
                    raise ValueError(
                        f"{self.name}: child {child.name!r} activates on infeasible {pv!r}."
                    )
        return dataclasses.replace(self, children=self.children + added)

    def clear_external_type(self) -> "ParameterConfig":
        return dataclasses.replace(self, external_type=ExternalType.INTERNAL)

    # --- value helpers ----------------------------------------------------

    def cast_value(self, value: ParameterValueTypes) -> ParameterValueTypes:
        """Casts a raw value to this parameter's canonical python type."""
        if self.type == ParameterType.DOUBLE:
            return float(value)  # type: ignore[arg-type]
        if self.type == ParameterType.INTEGER:
            return int(value)  # type: ignore[arg-type]
        if self.type == ParameterType.DISCRETE:
            return float(value)  # type: ignore[arg-type]
        if self.type == ParameterType.CATEGORICAL:
            return str(value)
        return value

    def first_feasible_value(self) -> ParameterValueTypes:
        if self.default_value is not None:
            return self.default_value
        if self.type == ParameterType.CUSTOM:
            raise InvalidParameterError(
                f"{self.name}: CUSTOM parameter has no default value to seed with."
            )
        if self.type == ParameterType.DOUBLE:
            lo, hi = self.bounds
            return (lo + hi) / 2.0
        if self.type == ParameterType.INTEGER:
            # Arithmetic, not feasible_values[0]: wide integer ranges must not
            # materialize the whole range.
            return int(self._bounds[0])  # type: ignore[index]
        return self._feasible_values[0]


class InvalidParameterError(Exception):
    """A parameter value is infeasible for its config."""


@dataclasses.dataclass
class SearchSpaceSelector:
    """Fluent builder handle over a location in the (conditional) space.

    A selector addresses either the root of a ``SearchSpace`` or a parameter
    (by path of ``(name, activating values)`` pairs). ``add_*_param`` on a
    root selector appends a top-level parameter; on a parameter selector with
    selected values it appends a conditional child active for those values.
    """

    _space: "SearchSpace"
    # Path from root: each element is (param_name, parent_values or None).
    _path: Tuple[Tuple[str, Optional[Tuple[ParameterValueTypes, ...]]], ...] = ()

    # -- selection --

    def select_values(self, values: Sequence[ParameterValueTypes]) -> "SearchSpaceSelector":
        if not self._path:
            raise ValueError("select_values requires a selected parameter.")
        name, _ = self._path[-1]
        return SearchSpaceSelector(self._space, self._path[:-1] + ((name, tuple(values)),))

    def select(
        self, name: str, values: Optional[Sequence[ParameterValueTypes]] = None
    ) -> "SearchSpaceSelector":
        vals = tuple(values) if values is not None else None
        return SearchSpaceSelector(self._space, self._path + ((name, vals),))

    @property
    def parameter_name(self) -> str:
        if not self._path:
            raise ValueError("Root selector has no parameter name.")
        return self._path[-1][0]

    # -- builders --

    def _add(self, config: ParameterConfig) -> "SearchSpaceSelector":
        self._space._insert(self._path, config)
        return SearchSpaceSelector(self._space, self._path + ((config.name, None),))

    def add(self, config: ParameterConfig) -> "SearchSpaceSelector":
        """Adds a pre-built ParameterConfig at this location (top-level on a
        root selector; conditional child on a value-selected parameter)."""
        return self._add(config)

    @staticmethod
    def _indexed_name(name: str, index: Optional[int]) -> str:
        """``('rate', 0) -> 'rate[0]'`` multi-dimensional naming (reference
        ``_get_parameter_names_to_create``); ``index=None`` is a no-op."""
        if index is None:
            return name
        if index < 0:
            raise ValueError(f"{name}: index must be >= 0, got {index}.")
        return f"{name}[{index}]"

    @classmethod
    def parse_multi_dimensional_parameter_name(
        cls, name: str
    ) -> Optional[Tuple[str, int]]:
        """``'rate[10]' -> ('rate', 10)``; None when not multi-dimensional."""
        match = re.fullmatch(r"(?P<name>[^()]*)\[(?P<index>\d+)\]", name)
        if match is None:
            return None
        return match.group("name"), int(match.group("index"))

    def add_float_param(
        self,
        name: str,
        min_value: float,
        max_value: float,
        *,
        default_value: Optional[float] = None,
        scale_type: Optional[ScaleType] = ScaleType.LINEAR,
        index: Optional[int] = None,
    ) -> "SearchSpaceSelector":
        return self._add(
            ParameterConfig.factory(
                self._indexed_name(name, index),
                bounds=(float(min_value), float(max_value)),
                scale_type=scale_type,
                default_value=default_value,
            )
        )

    def add_int_param(
        self,
        name: str,
        min_value: int,
        max_value: int,
        *,
        default_value: Optional[int] = None,
        scale_type: Optional[ScaleType] = None,
        index: Optional[int] = None,
    ) -> "SearchSpaceSelector":
        if int(min_value) != min_value or int(max_value) != max_value:
            raise ValueError(f"{name}: integer bounds required, got {(min_value, max_value)}.")
        return self._add(
            ParameterConfig.factory(
                self._indexed_name(name, index),
                bounds=(int(min_value), int(max_value)),
                scale_type=scale_type,
                default_value=default_value,
            )
        )

    def add_discrete_param(
        self,
        name: str,
        feasible_values: Sequence[Union[int, float]],
        *,
        default_value: Optional[Union[int, float]] = None,
        scale_type: Optional[ScaleType] = ScaleType.LINEAR,
        auto_cast: bool = True,
        index: Optional[int] = None,
    ) -> "SearchSpaceSelector":
        external = ExternalType.INTERNAL
        if auto_cast and all(isinstance(v, int) or float(v).is_integer() for v in feasible_values):
            external = ExternalType.INTEGER
        return self._add(
            ParameterConfig.factory(
                self._indexed_name(name, index),
                feasible_values=list(feasible_values),
                scale_type=scale_type,
                default_value=default_value,
                external_type=external,
            )
        )

    def add_categorical_param(
        self,
        name: str,
        feasible_values: Sequence[str],
        *,
        default_value: Optional[str] = None,
        index: Optional[int] = None,
    ) -> "SearchSpaceSelector":
        return self._add(
            ParameterConfig.factory(
                self._indexed_name(name, index),
                feasible_values=list(feasible_values),
                default_value=default_value,
            )
        )

    def add_bool_param(
        self,
        name: str,
        *,
        default_value: Optional[bool] = None,
        index: Optional[int] = None,
    ) -> "SearchSpaceSelector":
        default = None if default_value is None else ("True" if default_value else "False")
        return self._add(
            ParameterConfig.factory(
                self._indexed_name(name, index),
                feasible_values=["False", "True"],
                default_value=default,
                external_type=ExternalType.BOOLEAN,
            )
        )

    def add_custom_param(
        self, name: str, *, default_value: Optional[ParameterValueTypes] = None
    ) -> "SearchSpaceSelector":
        """An opaque CUSTOM parameter: carried through trials, never modeled."""
        return self._add(
            ParameterConfig.factory(name, default_value=default_value)
        )


class SearchSpace:
    """An ordered collection of (possibly conditional) parameter configs."""

    def __init__(self, parameters: Sequence[ParameterConfig] = ()):
        self._parameters: List[ParameterConfig] = list(parameters)
        names = [p.name for p in self.all_parameters()]
        if len(set(names)) != len(names):
            raise ValueError(f"Duplicate parameter names in search space: {names}")

    # -- builders / selection --

    @property
    def root(self) -> SearchSpaceSelector:
        return SearchSpaceSelector(self)

    def select(self, name: str) -> SearchSpaceSelector:
        return SearchSpaceSelector(self).select(name)

    def select_root(self) -> SearchSpaceSelector:  # reference-compat alias
        return self.root

    # -- accessors --

    @property
    def parameters(self) -> List[ParameterConfig]:
        """Top-level parameter configs (children hang off these)."""
        return list(self._parameters)

    @parameters.setter
    def parameters(self, configs: Sequence[ParameterConfig]) -> None:
        self._parameters = list(configs)

    def all_parameters(self) -> List[ParameterConfig]:
        """All configs in pre-order, including conditional children."""
        out: List[ParameterConfig] = []
        for p in self._parameters:
            out.extend(p.traverse())
        return out

    def parameter_names(self, include_children: bool = True) -> List[str]:
        configs = self.all_parameters() if include_children else self._parameters
        return [p.name for p in configs]

    def get(self, name: str) -> ParameterConfig:
        for p in self.all_parameters():
            if p.name == name:
                return p
        raise KeyError(f"No parameter named {name!r} in search space.")

    def __contains__(self, name: str) -> bool:
        return any(p.name == name for p in self.all_parameters())

    def pop(self, name: str) -> ParameterConfig:
        """Removes and returns a top-level parameter."""
        for i, p in enumerate(self._parameters):
            if p.name == name:
                return self._parameters.pop(i)
        raise KeyError(f"No top-level parameter named {name!r}.")

    def num_parameters(self, of_type: Optional[ParameterType] = None) -> int:
        params = self.all_parameters()
        if of_type is None:
            return len(params)
        return sum(1 for p in params if p.type == of_type)

    @property
    def is_conditional(self) -> bool:
        return any(p.children for p in self._parameters)

    def is_empty(self) -> bool:
        return not self._parameters

    # -- semantics --

    def contains(self, parameters: Dict[str, Any]) -> bool:
        """Whether a {name: value} assignment is a feasible point.

        Values may be raw python values or objects with a ``.value`` attr.
        Every assigned name must exist and be feasible; every *active*
        parameter (parent chain matches) must be assigned; inactive
        parameters must not be assigned.
        """
        try:
            self.assert_contains(parameters)
            return True
        except InvalidParameterError:
            return False

    def assert_contains(self, parameters: Dict[str, Any]) -> None:
        def raw(v: Any) -> ParameterValueTypes:
            return v.value if hasattr(v, "value") else v

        assigned = {k: raw(v) for k, v in parameters.items()}
        known = {p.name for p in self.all_parameters()}
        for name in assigned:
            if name not in known:
                raise InvalidParameterError(f"Unknown parameter {name!r}.")

        def check(config: ParameterConfig, active: bool) -> None:
            if active:
                if config.name not in assigned:
                    raise InvalidParameterError(f"Missing active parameter {config.name!r}.")
                value = assigned[config.name]
                if not config.contains(value):
                    raise InvalidParameterError(
                        f"Value {value!r} infeasible for parameter {config.name!r}."
                    )
            elif config.name in assigned:
                raise InvalidParameterError(
                    f"Inactive conditional parameter {config.name!r} was assigned."
                )
            for child in config.children:
                child_active = active and config.name in assigned and any(
                    _parent_value_matches(assigned[config.name], pv)
                    for pv in child.matching_parent_values
                )
                check(child, child_active)

        for p in self._parameters:
            check(p, True)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, SearchSpace):
            return NotImplemented
        return self._parameters == other._parameters

    def __repr__(self) -> str:
        return f"SearchSpace({self._parameters!r})"

    def __deepcopy__(self, memo: Dict[int, Any]) -> "SearchSpace":
        return SearchSpace(copy.deepcopy(self._parameters, memo))

    # -- internal insertion used by selectors --

    def _insert(
        self,
        path: Tuple[Tuple[str, Optional[Tuple[ParameterValueTypes, ...]]], ...],
        config: ParameterConfig,
    ) -> None:
        if config.name in self:
            raise ValueError(f"Parameter {config.name!r} already exists.")
        if not path:
            self._parameters.append(config)
            return

        def insert_into(parent: ParameterConfig, remaining) -> ParameterConfig:
            if not remaining:
                raise AssertionError("empty path")
            name, values = remaining[0]
            if parent.name != name:
                raise KeyError(f"Expected {name!r}, found {parent.name!r}.")
            if len(remaining) == 1:
                if values is None:
                    raise ValueError(
                        f"Adding a conditional child under {name!r} requires "
                        "select_values(...) to pick activating parent values."
                    )
                return parent.add_children([(values, config)])
            new_children = []
            found = False
            for child in parent.children:
                if child.name == remaining[1][0]:
                    found = True
                    new_children.append(insert_into(child, remaining[1:]))
                else:
                    new_children.append(child)
            if not found:
                raise KeyError(f"No child {remaining[1][0]!r} under {parent.name!r}.")
            return dataclasses.replace(parent, children=tuple(new_children))

        for i, top in enumerate(self._parameters):
            if top.name == path[0][0]:
                self._parameters[i] = insert_into(top, path)
                return
        raise KeyError(f"No top-level parameter named {path[0][0]!r}.")


def parent_value_matches(
    assigned: ParameterValueTypes, parent_value: ParameterValueTypes
) -> bool:
    """Whether an assigned parent value activates a child keyed on parent_value.

    The single source of truth for conditional activation — used by
    ``SearchSpace.assert_contains``, the random/default samplers, and the
    service converters. Numerics compare with tolerance, strings exactly.
    """
    if isinstance(assigned, str) or isinstance(parent_value, str):
        return str(assigned) == str(parent_value)
    return _is_close(float(assigned), float(parent_value))


_parent_value_matches = parent_value_matches  # internal alias
