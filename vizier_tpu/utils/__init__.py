"""Utilities: profiling, JSON codecs, serializable ABCs, validators."""

from vizier_tpu.utils.json_utils import NumpyDecoder, NumpyEncoder, dumps, loads
from vizier_tpu.utils.profiler import (
    collect_events,
    record_runtime,
    record_tracing,
    timeit,
)
from vizier_tpu.utils.serializable import (
    DecodeError,
    PartiallySerializable,
    Serializable,
)
