"""Numpy-aware JSON encoding for metadata serialization.

Parity with ``/root/reference/vizier/utils/json_utils.py:27,56``: arrays are
encoded as ``{"__np__": {dtype, shape, data}}`` so designer state containing
numpy/JAX arrays round-trips through string metadata.
"""

from __future__ import annotations

import base64
import json
from typing import Any

import numpy as np


class NumpyEncoder(json.JSONEncoder):
    def default(self, obj: Any) -> Any:
        if isinstance(obj, np.ndarray):
            return {
                "__np__": {
                    "dtype": str(obj.dtype),
                    "shape": list(obj.shape),
                    "data": base64.b64encode(np.ascontiguousarray(obj).tobytes()).decode("ascii"),
                }
            }
        if isinstance(obj, (np.integer,)):
            return int(obj)
        if isinstance(obj, (np.floating,)):
            return float(obj)
        if isinstance(obj, (np.bool_,)):
            return bool(obj)
        if hasattr(obj, "__array__"):  # jax arrays
            return self.default(np.asarray(obj))
        return super().default(obj)


def _object_hook(d: dict) -> Any:
    if "__np__" in d and set(d) == {"__np__"}:
        spec = d["__np__"]
        arr = np.frombuffer(
            base64.b64decode(spec["data"]), dtype=np.dtype(spec["dtype"])
        ).reshape(spec["shape"])
        return arr.copy()
    return d


class NumpyDecoder(json.JSONDecoder):
    """Inverse of :class:`NumpyEncoder` (``json.loads(s, cls=NumpyDecoder)``)."""

    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("object_hook", _object_hook)
        super().__init__(**kwargs)


def dumps(obj: Any) -> str:
    return json.dumps(obj, cls=NumpyEncoder)


def loads(s: str) -> Any:
    return json.loads(s, object_hook=_object_hook)
