"""Tracing/profiling: timers, runtime decorators, and retrace beacons.

Parity with ``/root/reference/vizier/utils/profiler.py`` (global event
storage ``:68-121``, ``collect_events`` ``:138``, ``timeit`` ``:156``,
``record_runtime`` ``:213`` with ``block_until_ready`` for async accelerator
dispatch, ``record_tracing`` ``:291``). Retraces are the #1 perf bug in the
JAX layer; ``record_tracing`` makes them visible.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import datetime
import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class ProfileEvent:
    name: str
    kind: str  # 'latency' | 'tracing'
    duration_secs: float
    timestamp: float


class _Storage:
    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[ProfileEvent] = []
        self._enabled = False
        self._scope: List[str] = []

    def add(self, event: ProfileEvent) -> None:
        with self._lock:
            if self._enabled:
                self._events.append(event)

    def scoped_name(self, name: str) -> str:
        with self._lock:
            return "::".join(self._scope + [name])

    @contextlib.contextmanager
    def push_scope(self, name: str):
        with self._lock:
            self._scope.append(name)
        try:
            yield
        finally:
            with self._lock:
                self._scope.pop()

    @contextlib.contextmanager
    def collect(self):
        with self._lock:
            self._enabled = True
            self._events = []
        try:
            yield self._events
        finally:
            with self._lock:
                self._enabled = False


_storage = _Storage()

_tracing_mod = None


def _tracer():
    """The observability tracer, lazily bound (no import cycle: the
    observability package never imports utils.profiler)."""
    global _tracing_mod
    if _tracing_mod is None:
        from vizier_tpu.observability import tracing as _tracing_mod_

        _tracing_mod = _tracing_mod_
    return _tracing_mod.get_tracer()


def collect_events():
    """Context manager enabling collection; yields the event list."""
    return _storage.collect()


@contextlib.contextmanager
def timeit(name: str, also_log: bool = False):
    """Times a block (nested scopes join with ``::``).

    Also opens a ``profiler.<name>`` span on the observability tracer, so
    the per-phase timers that already annotate the designer hot path
    (convert_trials, train_gp, acquisition_optimizer, ...) show up inside
    the request's trace for free. A no-op CM when tracing is off.
    """
    full = _storage.scoped_name(name)
    start = time.perf_counter()
    with _storage.push_scope(name), _tracer().span(f"profiler.{name}"):
        yield
    duration = time.perf_counter() - start
    _storage.add(
        ProfileEvent(name=full, kind="latency", duration_secs=duration, timestamp=time.time())
    )
    if also_log:
        import logging

        logging.getLogger(__name__).info("%s took %.3fs", full, duration)


def record_runtime(
    fn: Optional[Callable] = None,
    *,
    name_prefix: str = "",
    name: str = "",
    also_log: bool = False,
    block_until_ready: bool = False,
):
    """Decorator recording a function's wall time.

    ``block_until_ready=True`` waits for async accelerator dispatch so the
    recorded time covers device execution, not just tracing/enqueue.
    """

    def decorator(func: Callable) -> Callable:
        label = "::".join(x for x in (name_prefix, name or func.__qualname__) if x)

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            with timeit(label, also_log=also_log):
                out = func(*args, **kwargs)
                if block_until_ready:
                    import jax

                    out = jax.block_until_ready(out)
            return out

        return wrapper

    if fn is not None:
        return decorator(fn)
    return decorator


def record_tracing(fn: Optional[Callable] = None, *, name: str = ""):
    """Decorator that logs a 'tracing' event each time the body is traced.

    Wrap the *traced* function (the one passed to jit): each execution of
    the python body is a (re)trace — frequent events mean the jit cache is
    missing (shape instability), the top perf bug to hunt.
    """

    def decorator(func: Callable) -> Callable:
        label = name or func.__qualname__

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            _storage.add(
                ProfileEvent(
                    name=label, kind="tracing", duration_secs=0.0, timestamp=time.time()
                )
            )
            return func(*args, **kwargs)

        return wrapper

    if fn is not None:
        return decorator(fn)
    return decorator


def get_latencies_dict(
    events: List[ProfileEvent],
) -> Dict[str, List[datetime.timedelta]]:
    out: Dict[str, List[datetime.timedelta]] = collections.defaultdict(list)
    for e in events:
        if e.kind == "latency":
            out[e.name].append(datetime.timedelta(seconds=e.duration_secs))
    return dict(out)


def get_tracing_counts(events: List[ProfileEvent]) -> Dict[str, int]:
    out: Dict[str, int] = collections.defaultdict(int)
    for e in events:
        if e.kind == "tracing":
            out[e.name] += 1
    return dict(out)
