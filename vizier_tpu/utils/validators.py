"""Reusable value validators for dataclass ``__post_init__`` checks.

Parity with ``/root/reference/vizier/utils/attrs_utils.py`` — the
reference wires these into attrs fields; this project's dataclasses call
them directly in ``__post_init__`` (same checks, no attrs dependency).
Each raises ``ValueError`` with the offending field name.
"""

from __future__ import annotations

import re
from typing import Any, Collection, Optional, Tuple


def assert_not_empty(name: str, value: Collection) -> None:
    if not value:
        raise ValueError(f"{name} must not be empty.")


def assert_not_negative(name: str, value: float) -> None:
    if value < 0:
        raise ValueError(f"{name} must not be negative (got {value}).")


def assert_not_none(name: str, value: Any) -> None:
    if value is None:
        raise ValueError(f"{name} must not be None.")


def assert_between(name: str, value: float, low: float, high: float) -> None:
    if value < low or value > high:
        raise ValueError(f"{name} ({value}) must be between {low} and {high}.")


def assert_re_fullmatch(name: str, value: str, regex: str) -> None:
    if not re.fullmatch(regex, value):
        raise ValueError(f"{name} ({value!r}) must fully match {regex!r}.")


def assert_shape(
    name: str, value, expected: Tuple[Optional[int], ...]
) -> None:
    """Checks an array's shape; ``None`` entries match any extent."""
    shape = tuple(getattr(value, "shape", ()))
    if len(shape) != len(expected) or any(
        e is not None and s != e for s, e in zip(shape, expected)
    ):
        raise ValueError(f"{name} has shape {shape}; expected {expected}.")
