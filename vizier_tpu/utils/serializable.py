"""Serialization contracts for algorithm-state checkpointing.

Parity with ``/root/reference/vizier/interfaces/serializable.py``: designers
checkpoint their state into study metadata; ``DecodeError`` signals that the
stored state is unusable and the caller must fall back to full trial replay.
"""

from __future__ import annotations

import abc

from vizier_tpu.pyvizier import common


class DecodeError(Exception):
    """Stored state could not be decoded; fall back to replay."""


class Serializable(abc.ABC):
    """State fully captured by ``dump``; ``recover`` rebuilds from scratch."""

    @classmethod
    @abc.abstractmethod
    def recover(cls, metadata: common.Metadata) -> "Serializable":
        """Rebuilds the object purely from dumped metadata (raises DecodeError)."""

    @abc.abstractmethod
    def dump(self) -> common.Metadata:
        """Serializes full state to metadata."""


class PartiallySerializable(abc.ABC):
    """Object must be constructed normally, then ``load`` restores state."""

    @abc.abstractmethod
    def load(self, metadata: common.Metadata) -> None:
        """Restores state from dumped metadata (raises DecodeError)."""

    @abc.abstractmethod
    def dump(self) -> common.Metadata:
        """Serializes restorable state to metadata."""
