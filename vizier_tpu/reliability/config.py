"""Reliability knobs (retries, deadlines, breaker, fallback).

Everything defaults ON; ``VIZIER_RELIABILITY=0`` restores the seed's
fail-hard behavior wholesale, and each mechanism has its own off-switch for
A/B isolation:

- ``VIZIER_RELIABILITY=0``          — master switch: no retries, no deadline
  enforcement, no breaker, no fallback (one designer exception fails the op);
- ``VIZIER_RELIABILITY_RETRIES=0``  — client RPCs and op polling fail on the
  first transient error;
- ``VIZIER_RELIABILITY_DEADLINE=0`` — no deadline attachment/propagation;
- ``VIZIER_RELIABILITY_BREAKER=0``  — designer failures never open a circuit;
- ``VIZIER_RELIABILITY_FALLBACK=0`` — designer failures error the op instead
  of degrading to seeded quasi-random suggestions.
"""

from __future__ import annotations

import dataclasses

# All VIZIER_* switches are declared in (and read through) the central
# registry (vizier_tpu.analysis.registry); enforced by the env_registry
# analysis pass.
from vizier_tpu.analysis import registry as _registry


@dataclasses.dataclass(frozen=True)
class ReliabilityConfig:
    """Knobs for the fault-tolerant suggestion path."""

    # Master switch; off restores fail-hard seed behavior everywhere.
    enabled: bool = True
    # Per-mechanism switches (each effective only when ``enabled``).
    retries: bool = True
    deadlines: bool = True
    breaker: bool = True
    fallback: bool = True

    # Retry: exponential backoff with full jitter over transient errors.
    retry_max_attempts: int = 3
    retry_base_delay_secs: float = 0.1
    retry_max_delay_secs: float = 2.0

    # Deadline budget the client attaches to SuggestTrials when the caller
    # supplies none. Kept under the 600 s polling timeout so an over-budget
    # computation surfaces as a typed error instead of a poll timeout.
    default_deadline_secs: float = 300.0

    # Circuit breaker: ``failure_threshold`` failures within ``window_secs``
    # open the circuit; after ``cooldown_secs`` it half-opens and admits
    # ``half_open_probes`` trial computations.
    breaker_failure_threshold: int = 3
    breaker_window_secs: float = 60.0
    breaker_cooldown_secs: float = 30.0
    breaker_half_open_probes: int = 1

    # -- effective switches (master ANDed in) ------------------------------

    @property
    def retries_on(self) -> bool:
        return self.enabled and self.retries

    @property
    def deadlines_on(self) -> bool:
        return self.enabled and self.deadlines

    @property
    def breaker_on(self) -> bool:
        return self.enabled and self.breaker

    @property
    def fallback_on(self) -> bool:
        return self.enabled and self.fallback

    @classmethod
    def from_env(cls) -> "ReliabilityConfig":
        """The default config with per-knob environment overrides applied."""
        return cls(
            enabled=_registry.env_on("VIZIER_RELIABILITY"),
            retries=_registry.env_on("VIZIER_RELIABILITY_RETRIES"),
            deadlines=_registry.env_on("VIZIER_RELIABILITY_DEADLINE"),
            breaker=_registry.env_on("VIZIER_RELIABILITY_BREAKER"),
            fallback=_registry.env_on("VIZIER_RELIABILITY_FALLBACK"),
        )

    @classmethod
    def disabled(cls) -> "ReliabilityConfig":
        """Seed behavior: fail hard, no retries/deadlines/breaker/fallback."""
        return cls(enabled=False)
