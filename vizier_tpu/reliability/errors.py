"""Typed reliability errors + transient/permanent classification.

The service surfaces failures to clients as text inside the long-running
operation's ``error`` field, so the transient/permanent distinction must
survive a round of stringification: transient errors carry a leading
``TRANSIENT:`` marker that retry logic greps for, while typed exceptions
cover the in-process paths.

Permanent errors (e.g. an invalid search space or unknown algorithm) are
deliberately NOT marked: retrying them burns the client's budget on a
failure that will never heal, and falling back would silently serve
quasi-random points to a misconfigured study forever.
"""

from __future__ import annotations

import re
from typing import Optional, Union

TRANSIENT_MARKER = "TRANSIENT:"

# Admission-control shed vocabulary: the marker names the condition
# (capacity, not failure) and the retry-after key carries the service's
# backoff hint in milliseconds. Both survive stringification across the
# op-error round trip, like the transient marker itself.
RESOURCE_EXHAUSTED_MARKER = "RESOURCE_EXHAUSTED"
RETRY_AFTER_KEY = "retry_after_ms="
_RETRY_AFTER_RE = re.compile(re.escape(RETRY_AFTER_KEY) + r"([0-9]*\.?[0-9]+)")


class TransientError(RuntimeError):
    """A failure that is expected to heal: safe to retry."""


class DeadlineExceededError(TransientError, TimeoutError):
    """The request's deadline budget ran out (typed DEADLINE_EXCEEDED)."""


class CircuitOpenError(TransientError):
    """The study's circuit breaker is open; computation was not attempted."""


def mark_transient(text: str) -> str:
    """Prefixes ``text`` with the marker unless one is already present."""
    if has_transient_marker(text):
        return text
    return f"{TRANSIENT_MARKER} {text}"


def has_transient_marker(text: str) -> bool:
    """True when error text anywhere carries the transient marker.

    Substring (not prefix) match: service layers wrap each other's error
    text (``"RuntimeError: Pythia error: TRANSIENT: ..."``), and the marker
    must survive that nesting.
    """
    return TRANSIENT_MARKER in text


def is_resource_exhausted(text: str) -> bool:
    """True when error text carries the admission-shed marker (substring:
    service layers wrap each other's error text, like the transient
    marker)."""
    return RESOURCE_EXHAUSTED_MARKER in text


def retry_after_secs(error: Union[BaseException, str]) -> Optional[float]:
    """The ``retry_after_ms=`` hint in an error (or its text), in seconds.

    Admission sheds stamp the hint so client retry logic can honor the
    service's backoff floor instead of hammering a saturated fleet with
    its own (possibly tiny) jittered schedule. None when absent.
    """
    match = _RETRY_AFTER_RE.search(
        error if isinstance(error, str) else str(error)
    )
    if match is None:
        return None
    try:
        return float(match.group(1)) / 1e3
    except ValueError:  # pragma: no cover - regex admits only numbers
        return None


def is_transient_exception(error: BaseException) -> bool:
    """Classifies an exception as retryable.

    Transient: the typed reliability errors, timeouts, transport failures
    (``ConnectionError``, gRPC UNAVAILABLE / DEADLINE_EXCEEDED /
    RESOURCE_EXHAUSTED), and any error whose text carries the marker.
    """
    if isinstance(error, (TransientError, TimeoutError, ConnectionError)):
        return True
    if has_transient_marker(str(error)):
        return True
    code = getattr(error, "code", None)
    if callable(code):
        try:
            import grpc

            if isinstance(error, grpc.RpcError):
                return code() in (
                    grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                )
        except Exception:  # grpc missing or a non-RPC ``code`` attribute
            return False
    return False


def format_op_error(error: BaseException) -> str:
    """Formats an exception for an operation/response ``error`` field.

    Transient errors gain the ``TRANSIENT:`` marker (once — re-wrapped
    errors whose text already carries it are left alone) so clients can
    classify without the exception object.
    """
    text = f"{type(error).__name__}: {error}"
    if is_transient_exception(error):
        return mark_transient(text)
    return text
