"""Per-study circuit breaker over the designer computation.

Classic closed → open → half-open automaton with a sliding failure window:
``failure_threshold`` designer failures within ``window_secs`` open the
circuit; while open, computations are short-circuited (the caller degrades
to fallback or a typed error instead of burning a designer run that will
very likely fail); after ``cooldown_secs`` the circuit half-opens and
admits ``half_open_probes`` probe computations — one success closes it, one
failure re-opens it.

Per *study*, not per process: one study whose designer state is wedged
(e.g. a GP train that NaNs on its particular history) must not poison
suggestions for every other study the process serves.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# transition-target state -> serving-stats counter
_TRANSITION_COUNTERS = {
    OPEN: "breaker_open_transitions",
    HALF_OPEN: "breaker_half_open_transitions",
    CLOSED: "breaker_close_transitions",
}


class CircuitBreaker:
    """One study's failure automaton (thread-safe)."""

    def __init__(
        self,
        *,
        failure_threshold: int = 3,
        window_secs: float = 60.0,
        cooldown_secs: float = 30.0,
        half_open_probes: int = 1,
        time_fn: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ):
        self._failure_threshold = max(1, failure_threshold)
        self._window_secs = window_secs
        self._cooldown_secs = cooldown_secs
        self._half_open_probes = max(1, half_open_probes)
        self._time_fn = time_fn
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures: Deque[float] = collections.deque()
        self._opened_at = 0.0
        self._probes_in_flight = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, new_state: str) -> None:
        # Caller holds the lock; the callback runs inside it too (counter
        # increments only — keep it that way).
        old, self._state = self._state, new_state
        if self._on_transition is not None and old != new_state:
            self._on_transition(old, new_state)

    def allow(self) -> bool:
        """Whether a designer computation may start right now."""
        with self._lock:
            now = self._time_fn()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now - self._opened_at < self._cooldown_secs:
                    return False
                self._transition(HALF_OPEN)
                self._probes_in_flight = 1
                return True
            # HALF_OPEN: admit a bounded number of concurrent probes.
            if self._probes_in_flight < self._half_open_probes:
                self._probes_in_flight += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._transition(CLOSED)
                self._probes_in_flight = 0
            self._failures.clear()

    def record_failure(self) -> None:
        with self._lock:
            now = self._time_fn()
            if self._state == HALF_OPEN:
                # The probe failed: straight back to open, fresh cooldown.
                self._transition(OPEN)
                self._opened_at = now
                self._probes_in_flight = 0
                self._failures.clear()
                return
            if self._state == OPEN:
                return  # a straggler admitted before opening; already open
            self._failures.append(now)
            while self._failures and now - self._failures[0] > self._window_secs:
                self._failures.popleft()
            if len(self._failures) >= self._failure_threshold:
                self._transition(OPEN)
                self._opened_at = now
                self._failures.clear()


class CircuitBreakerRegistry:
    """Per-study breakers sharing one config and one stats sink."""

    def __init__(
        self,
        *,
        failure_threshold: int = 3,
        window_secs: float = 60.0,
        cooldown_secs: float = 30.0,
        half_open_probes: int = 1,
        time_fn: Callable[[], float] = time.monotonic,
        stats=None,  # serving.ServingStats (duck-typed: .increment(name))
    ):
        self._kwargs = dict(
            failure_threshold=failure_threshold,
            window_secs=window_secs,
            cooldown_secs=cooldown_secs,
            half_open_probes=half_open_probes,
            time_fn=time_fn,
        )
        self._stats = stats
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}

    def _count_transition(self, study_name: str, old: str, new: str) -> None:
        if self._stats is not None:
            self._stats.increment(_TRANSITION_COUNTERS[new])
        # Transitions fire inside the suggest computation that tripped (or
        # probed) the breaker — stamp them on that span, and on the study's
        # flight-recorder ring (both leaf sinks). Lazy import: reliability
        # must stay importable without the serving stack.
        from vizier_tpu.observability import flight_recorder as recorder_lib
        from vizier_tpu.observability import tracing as tracing_lib

        tracing_lib.add_current_event(
            "breaker.transition", from_state=old, to_state=new
        )
        recorder_lib.get_recorder().record(
            study_name, "breaker_transition", from_state=old, to_state=new
        )

    def get(self, study_name: str) -> CircuitBreaker:
        with self._lock:
            breaker = self._breakers.get(study_name)
            if breaker is None:
                breaker = CircuitBreaker(
                    on_transition=(
                        lambda old, new, _study=study_name: (
                            self._count_transition(_study, old, new)
                        )
                    ),
                    **self._kwargs,
                )
                self._breakers[study_name] = breaker
            return breaker

    def invalidate(self, study_name: str) -> bool:
        """Drops the study's breaker (study deleted / state reset)."""
        with self._lock:
            return self._breakers.pop(study_name, None) is not None

    def states(self) -> Dict[str, str]:
        """study -> breaker state, for observability snapshots."""
        # Snapshot the map under the registry lock, read each breaker's
        # state OUTSIDE it: b.state takes the breaker's own lock, and the
        # registry lock must stay map bookkeeping only (the runtime
        # lock-order cross-check flagged the nested read).
        with self._lock:
            breakers = list(self._breakers.items())
        return {name: b.state for name, b in breakers}

    def open_count(self) -> int:
        return sum(1 for s in self.states().values() if s != CLOSED)
