"""Fault tolerance for the suggestion path: retries, deadlines, breaker, fallback.

The seed's failure story was fail-hard everywhere: one designer exception
failed the ``SuggestTrials`` op, clients polled with a fixed sleep and no
retries, and nothing bounded how long a wedged GP train could hold a
study's frontier. This package threads graceful degradation through
client → VizierService → Pythia → designer:

- :class:`RetryPolicy` — exponential backoff + full jitter over transient
  errors, applied to client RPCs and op polling;
- :class:`Deadline` — a budget attached at the client, decremented across
  hops, enforced around the designer computation; over-budget work completes
  the op with a typed ``TRANSIENT: DEADLINE_EXCEEDED:`` error;
- :class:`CircuitBreaker` / :class:`CircuitBreakerRegistry` — per-study
  closed/open/half-open automaton over a sliding designer-failure window;
- :func:`suggest_fallback` — on designer failure or open circuit, seeded
  quasi-random suggestions stamped ``reliability:fallback=quasi_random``
  keep the study moving (auditable degradation, arxiv 2408.11527 §the
  production service; regret-preserving fill-in per arxiv 1206.6402);
- :class:`ReliabilityConfig` — the knobs; ``VIZIER_RELIABILITY=0`` restores
  the seed's fail-hard behavior (see ``docs/guides/reliability.md``).

Counters land in the serving stats (``PythiaServicer.serving_stats()``):
retries, fallbacks, breaker transitions, deadline hits. The deterministic
chaos harness exercising all of this is ``vizier_tpu.testing.chaos``.
"""

from vizier_tpu.reliability.breaker import CircuitBreaker
from vizier_tpu.reliability.breaker import CircuitBreakerRegistry
from vizier_tpu.reliability.config import ReliabilityConfig
from vizier_tpu.reliability.deadline import Deadline
from vizier_tpu.reliability.errors import CircuitOpenError
from vizier_tpu.reliability.errors import DeadlineExceededError
from vizier_tpu.reliability.errors import TRANSIENT_MARKER
from vizier_tpu.reliability.errors import TransientError
from vizier_tpu.reliability.errors import format_op_error
from vizier_tpu.reliability.errors import has_transient_marker
from vizier_tpu.reliability.errors import is_transient_exception
from vizier_tpu.reliability.errors import mark_transient
from vizier_tpu.reliability.fallback import FALLBACK_NAMESPACE
from vizier_tpu.reliability.fallback import is_fallback_suggestion
from vizier_tpu.reliability.fallback import suggest_fallback
from vizier_tpu.reliability.retry import RetryPolicy

__all__ = [
    "CircuitBreaker",
    "CircuitBreakerRegistry",
    "CircuitOpenError",
    "Deadline",
    "DeadlineExceededError",
    "FALLBACK_NAMESPACE",
    "ReliabilityConfig",
    "RetryPolicy",
    "TRANSIENT_MARKER",
    "TransientError",
    "format_op_error",
    "has_transient_marker",
    "is_fallback_suggestion",
    "is_transient_exception",
    "mark_transient",
    "suggest_fallback",
]
