"""RetryPolicy: exponential backoff with full jitter over transient errors.

Full jitter (delay ~ Uniform(0, min(cap, base * 2^attempt))) rather than
equal/decorrelated jitter: with many clients hammering one service, full
jitter spreads the retry herd widest for the same mean delay. The RNG and
sleep function are injectable so tests run deterministic schedules without
real sleeping.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Iterator, Optional, TypeVar

from vizier_tpu.reliability import config as config_lib
from vizier_tpu.reliability import errors as errors_lib

_T = TypeVar("_T")


@dataclasses.dataclass
class RetryPolicy:
    """Bounded retries of transient failures."""

    max_attempts: int = 3
    base_delay_secs: float = 0.1
    max_delay_secs: float = 2.0
    jitter: bool = True
    is_retryable: Callable[[BaseException], bool] = (
        errors_lib.is_transient_exception
    )
    rng: random.Random = dataclasses.field(default_factory=random.Random)
    sleep_fn: Callable[[float], None] = time.sleep

    @classmethod
    def from_config(
        cls,
        config: config_lib.ReliabilityConfig,
        *,
        seed: Optional[int] = None,
    ) -> "RetryPolicy":
        """A policy matching ``config`` (1 attempt = no retries when off)."""
        return cls(
            max_attempts=config.retry_max_attempts if config.retries_on else 1,
            base_delay_secs=config.retry_base_delay_secs,
            max_delay_secs=config.retry_max_delay_secs,
            rng=random.Random(seed),
        )

    def delay_for_attempt(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (0-based)."""
        cap = min(self.max_delay_secs, self.base_delay_secs * (2.0**attempt))
        return self.rng.uniform(0.0, cap) if self.jitter else cap

    def delays(self) -> Iterator[float]:
        """The backoff schedule: one delay per allowed retry."""
        for attempt in range(max(0, self.max_attempts - 1)):
            yield self.delay_for_attempt(attempt)

    def call(
        self,
        fn: Callable[[], _T],
        *,
        on_retry: Optional[Callable[[BaseException, int], None]] = None,
        deadline=None,
    ) -> _T:
        """Runs ``fn``, retrying transient failures with backoff.

        ``on_retry(error, attempt)`` fires before each backoff (counter
        hooks). A ``deadline`` (reliability.Deadline) bounds the whole
        attempt loop: no retry is started that the remaining budget cannot
        cover, and the last error is re-raised instead. An error carrying
        a ``retry_after_ms=`` hint (an admission shed) raises the backoff
        to at least the service's floor — shed retries must not hammer a
        saturated fleet on the client's own (jittered, possibly tiny)
        schedule.
        """
        attempts = max(1, self.max_attempts)
        for attempt in range(attempts):
            try:
                return fn()
            except BaseException as e:  # noqa: B036 - classified below
                last_attempt = attempt == attempts - 1
                if last_attempt or not self.is_retryable(e):
                    raise
                delay = self.delay_for_attempt(attempt)
                hint = errors_lib.retry_after_secs(e)
                if hint is not None:
                    delay = max(delay, hint)
                if deadline is not None and deadline.remaining() <= delay:
                    raise
                if on_retry is not None:
                    on_retry(e, attempt)
                if delay > 0:
                    self.sleep_fn(delay)
        raise AssertionError("unreachable")  # pragma: no cover
