"""Deadline budgets: created at the edge, decremented across hops.

A deadline travels the wire as *remaining seconds* (clock-skew immune), and
in-process as a :class:`Deadline` pinned to a monotonic clock. Every layer
re-reads ``remaining()`` at its hop so queueing and compute time upstream
shrink the budget downstream.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from vizier_tpu.reliability import errors as errors_lib


class Deadline:
    """A fixed point in (monotonic) time with budget arithmetic."""

    def __init__(
        self,
        expires_at: Optional[float],
        clock: Callable[[], float] = time.monotonic,
    ):
        # None = no deadline (infinite budget).
        self._expires_at = expires_at
        self._clock = clock

    @classmethod
    def from_budget(
        cls, budget_secs: float, clock: Callable[[], float] = time.monotonic
    ) -> "Deadline":
        """A deadline ``budget_secs`` from now; <= 0 means none."""
        if budget_secs <= 0:
            return cls(None, clock)
        return cls(clock() + budget_secs, clock)

    @classmethod
    def from_wire(
        cls, budget_secs: float, clock: Callable[[], float] = time.monotonic
    ) -> "Deadline":
        """A deadline from a wire ``deadline_secs`` field.

        Wire semantics: positive = remaining budget, 0 = no deadline
        (back-compat), **negative = already expired at the sender** — the
        resulting deadline is born expired so the receiver's existing
        ``check()`` sheds the request before any computation starts,
        instead of conflating "caller gave up" with "no deadline".
        """
        if budget_secs == 0:
            return cls(None, clock)
        return cls(clock() + budget_secs, clock)

    @classmethod
    def none(cls) -> "Deadline":
        """No deadline: infinite remaining budget, never expired."""
        return cls(None)

    @property
    def is_set(self) -> bool:
        return self._expires_at is not None

    def remaining(self) -> float:
        """Seconds left (may be negative once expired; inf when unset)."""
        if self._expires_at is None:
            return float("inf")
        return self._expires_at - self._clock()

    @property
    def expired(self) -> bool:
        return self._expires_at is not None and self.remaining() <= 0

    def wire_budget(self) -> float:
        """The remaining budget as a request field (0 = no deadline)."""
        if self._expires_at is None:
            return 0.0
        return max(0.0, self.remaining())

    def check(self, what: str) -> None:
        """Raises the typed DEADLINE_EXCEEDED error once the budget is gone."""
        if self.expired:
            raise errors_lib.DeadlineExceededError(
                errors_lib.mark_transient(
                    f"DEADLINE_EXCEEDED: {what} "
                    f"(over budget by {-self.remaining():.3f}s)"
                )
            )
