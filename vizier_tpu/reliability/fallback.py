"""Graceful degradation: seeded quasi-random suggestions on designer failure.

The production Vizier service keeps issuing suggestions under algorithm
failure by degrading to simpler samplers instead of erroring studies
(arxiv 2408.11527), and quasi-random fill-in preserves parallel GP-bandit
regret guarantees (arxiv 1206.6402) — so this is principled degradation,
not a hack. Every fallback suggestion is stamped with
``ns "reliability": fallback=quasi_random`` in trial metadata so degraded
trials stay auditable after the fact.
"""

from __future__ import annotations

import hashlib
import logging
from typing import List

from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

_logger = logging.getLogger(__name__)

FALLBACK_NAMESPACE = "reliability"
FALLBACK_KEY = "fallback"
FALLBACK_VALUE = "quasi_random"
FALLBACK_REASON_KEY = "fallback_reason"


def _study_seed(study_name: str) -> int:
    """A stable per-study seed (deterministic across processes/restarts)."""
    digest = hashlib.sha256(study_name.encode("utf-8")).digest()
    return int.from_bytes(digest[:4], "big")


def is_fallback_suggestion(metadata) -> bool:
    """True when trial/suggestion metadata carries the fallback marker."""
    return metadata.ns(FALLBACK_NAMESPACE).get(FALLBACK_KEY) == FALLBACK_VALUE


def suggest_fallback(
    problem: base_study_config.ProblemStatement,
    count: int,
    *,
    study_name: str,
    max_trial_id: int,
    reason: str,
) -> List[trial_.TrialSuggestion]:
    """``count`` seeded quasi-random suggestions, stamped as fallbacks.

    The Halton stream is seeded per study and fast-forwarded by
    ``max_trial_id``, so consecutive fallbacks on a moving study advance
    through the sequence instead of replaying the same points, while two
    fallbacks at the same frontier (e.g. coalesced peers) are identical.
    Conditional search spaces (which Halton cannot flatten) degrade one
    step further, to seeded uniform random.
    """
    from vizier_tpu.designers import quasi_random, random as random_designer

    seed = _study_seed(study_name)
    try:
        designer = quasi_random.QuasiRandomDesigner(
            problem.search_space, seed=seed
        )
        designer._halton.fast_forward(max_trial_id)
    except ValueError:
        _logger.warning(
            "Quasi-random fallback unavailable for %s (conditional space); "
            "degrading to seeded uniform random.",
            study_name,
        )
        designer = random_designer.RandomDesigner(
            problem.search_space, seed=seed + max_trial_id
        )
    suggestions = list(designer.suggest(count))
    for s in suggestions:
        ns = s.metadata.ns(FALLBACK_NAMESPACE)
        ns[FALLBACK_KEY] = FALLBACK_VALUE
        ns[FALLBACK_REASON_KEY] = reason
    return suggestions
