"""Deliberately failing designers (fault injection).

Parity with ``/root/reference/vizier/_src/algorithms/testing/failing.py:29,46``.
"""

from __future__ import annotations

from typing import List, Optional

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.pyvizier import trial as trial_


class FailedSuggestError(Exception):
    pass


class FailingDesigner(core_lib.Designer):
    """Raises on every suggest."""

    def update(self, completed, all_active=core_lib.ActiveTrials()) -> None:
        del completed, all_active

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        raise FailedSuggestError("FailingDesigner always fails.")


class AlternateFailingDesigner(core_lib.Designer):
    """Fails every second suggest call (retry-path testing)."""

    def __init__(self, inner: core_lib.Designer):
        self._inner = inner
        self._calls = 0

    def update(self, completed, all_active=core_lib.ActiveTrials()) -> None:
        self._inner.update(completed, all_active)

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        self._calls += 1
        if self._calls % 2 == 1:
            raise FailedSuggestError("AlternateFailingDesigner fails on odd calls.")
        return list(self._inner.suggest(count))
