"""Statistical algorithm-comparison testers.

Parity with
``/root/reference/vizier/_src/algorithms/testing/comparator_runner.py:54,120``:
``EfficiencyComparisonTester`` (log-efficiency score of candidate vs
baseline over repeated runs) and ``SimpleRegretComparisonTester`` (one-sided
regret comparison), used by convergence tests to gate algorithm changes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.benchmarks.analyzers import convergence_curve as cc
from vizier_tpu.benchmarks.experimenters import base as experimenter_base
from vizier_tpu.benchmarks.runners import benchmark_runner, benchmark_state
from vizier_tpu.pyvizier import trial as trial_


class FailedComparisonTestError(Exception):
    """The candidate did not beat/meet the baseline."""


def _run_curves(
    experimenter: experimenter_base.Experimenter,
    factory: core_lib.DesignerFactory,
    *,
    num_trials: int,
    num_repeats: int,
    batch_size: int = 1,
    seed: int = 0,
) -> cc.ConvergenceCurve:
    curves = []
    problem = experimenter.problem_statement()
    metric = next(m for m in problem.metric_information if not m.is_safety_metric)
    converter = cc.ConvergenceCurveConverter(metric, flip_signs_for_min=True)
    for r in range(num_repeats):
        state = benchmark_state.BenchmarkState.from_designer_factory(
            experimenter, factory, seed=seed + r
        )
        benchmark_runner.BenchmarkRunner(
            [benchmark_runner.GenerateAndEvaluate(batch_size)],
            num_repeats=num_trials // batch_size,
        ).run(state)
        trials = state.algorithm.supporter.GetTrials(
            status_matches=trial_.TrialStatus.COMPLETED
        )
        curves.append(converter.convert(trials))
    return cc.ConvergenceCurve.align_xs(curves)


@dataclasses.dataclass
class EfficiencyComparisonTester:
    """Asserts the candidate is at least ``baseline - margin`` efficient."""

    num_trials: int = 50
    num_repeats: int = 3
    margin: float = 0.3

    def assert_better_efficiency(
        self,
        experimenter: experimenter_base.Experimenter,
        candidate_factory: core_lib.DesignerFactory,
        baseline_factory: core_lib.DesignerFactory,
        *,
        batch_size: int = 1,
        seed: int = 0,
    ) -> float:
        baseline = _run_curves(
            experimenter,
            baseline_factory,
            num_trials=self.num_trials,
            num_repeats=self.num_repeats,
            batch_size=batch_size,
            seed=seed,
        )
        candidate = _run_curves(
            experimenter,
            candidate_factory,
            num_trials=self.num_trials,
            num_repeats=self.num_repeats,
            batch_size=batch_size,
            seed=seed + 1000,
        )
        score = cc.LogEfficiencyConvergenceCurveComparator(baseline).score(candidate)
        if score < -self.margin:
            raise FailedComparisonTestError(
                f"Candidate log-efficiency {score:.3f} below -margin {-self.margin}."
            )
        return score


@dataclasses.dataclass
class SimpleRegretComparisonTester:
    """Asserts candidate's median simple regret <= baseline's + tolerance."""

    num_trials: int = 50
    num_repeats: int = 3
    tolerance: float = 0.0

    def assert_better_simple_regret(
        self,
        experimenter: experimenter_base.Experimenter,
        candidate_factory: core_lib.DesignerFactory,
        baseline_factory: core_lib.DesignerFactory,
        *,
        seed: int = 0,
    ) -> None:
        def final_median(factory, offset):
            curve = _run_curves(
                experimenter,
                factory,
                num_trials=self.num_trials,
                num_repeats=self.num_repeats,
                seed=seed + offset,
            )
            # Curves are flipped to INCREASING; bigger is better.
            return float(np.median(curve.ys[:, -1]))

        baseline = final_median(baseline_factory, 0)
        candidate = final_median(candidate_factory, 1000)
        if candidate + self.tolerance < baseline:
            raise FailedComparisonTestError(
                f"Candidate final objective {candidate:.4f} worse than "
                f"baseline {baseline:.4f} (tolerance {self.tolerance})."
            )
