"""Shared test fixtures, runners, and statistical comparison testers."""

from vizier_tpu.testing.comparator_runner import (
    EfficiencyComparisonTester,
    FailedComparisonTestError,
    SimpleRegretComparisonTester,
)
from vizier_tpu.testing.numpy_assertions import (
    assert_arraytree_allclose,
    assert_pytree_allclose,
)
from vizier_tpu.testing.simplekd_runner import (
    ConvergenceTestError,
    SimpleKDConvergenceTester,
)
from vizier_tpu.testing.test_runners import RandomMetricsRunner
