"""Deterministic seeded fault injection (chaos harness).

Extends ``testing/failing.py``'s deliberately-failing designers with
*probabilistic*, *seeded* fault injection at three layers of the stack:

- :class:`ChaosDesigner` — wraps any designer; each ``suggest`` (and
  optionally ``update``) draws from the chaos RNG and raises
  ``failing.FailedSuggestError`` with the configured probability;
- :class:`ChaosDataStore` — wraps a ``DataStore``; configured methods
  raise :class:`InjectedFaultError` (a ``ConnectionError``, so the
  reliability layer classifies it transient) *before* delegating, never
  leaving partial writes behind;
- :class:`ChaosServiceStub` — wraps a service stub / in-process servicer;
  injects transport-shaped faults into RPCs, exercising client retries.

All injection draws come from ONE ``random.Random(seed)`` behind a lock, so
a single-threaded run is exactly reproducible: same seed, same wrapped call
sequence → same faults. Latency injection (``latency_secs`` with
``latency_prob``) simulates slow dependencies for deadline tests.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.testing import failing


class InjectedFaultError(ConnectionError):
    """A chaos-injected transport/storage fault (classified transient)."""


class ChaosMonkey:
    """The seeded fault source shared by every chaos wrapper in a run."""

    def __init__(
        self,
        *,
        seed: int = 0,
        failure_prob: float = 0.1,
        latency_prob: float = 0.0,
        latency_secs: float = 0.0,
        sleep_fn: Callable[[float], None] = time.sleep,
    ):
        if not 0.0 <= failure_prob <= 1.0:
            raise ValueError(f"failure_prob must be in [0, 1], got {failure_prob}")
        self.seed = seed
        self.failure_prob = failure_prob
        self.latency_prob = latency_prob
        self.latency_secs = latency_secs
        self._sleep_fn = sleep_fn
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # site -> {"calls": n, "faults": n, "latencies": n}
        self._counts: Dict[str, Dict[str, int]] = {}

    def _site(self, site: str) -> Dict[str, int]:
        return self._counts.setdefault(
            site, {"calls": 0, "faults": 0, "latencies": 0}
        )

    def strike(self, site: str) -> None:
        """One injection point: maybe sleep, maybe raise (seeded draws).

        Always draws exactly two variates per call so the fault sequence
        is a pure function of (seed, call index) — independent of which
        probabilities are zero.
        """
        with self._lock:
            counts = self._site(site)
            counts["calls"] += 1
            fail = self._rng.random() < self.failure_prob
            lag = self._rng.random() < self.latency_prob
            if lag:
                counts["latencies"] += 1
            if fail:
                counts["faults"] += 1
        if lag and self.latency_secs > 0:
            self._sleep_fn(self.latency_secs)
        if fail:
            raise InjectedFaultError(f"chaos: injected fault at {site}")

    def counts(self) -> Dict[str, Dict[str, int]]:
        """Per-site injection accounting (copied snapshot)."""
        with self._lock:
            return {site: dict(c) for site, c in self._counts.items()}

    def total_faults(self) -> int:
        with self._lock:
            return sum(c["faults"] for c in self._counts.values())


class ChaosDesigner(core_lib.Designer):
    """Probabilistic-failure wrapper around any designer.

    The probabilistic sibling of ``failing.AlternateFailingDesigner``:
    faults arrive per the chaos RNG instead of every other call, raising
    the same ``failing.FailedSuggestError`` (a *designer* failure, not a
    transport one — the service should degrade, not retry transport).
    """

    def __init__(
        self,
        inner: core_lib.Designer,
        chaos: ChaosMonkey,
        *,
        fail_updates: bool = False,
    ):
        self._inner = inner
        self._chaos = chaos
        self._fail_updates = fail_updates

    def update(self, completed, all_active=core_lib.ActiveTrials()) -> None:
        if self._fail_updates:
            try:
                self._chaos.strike("designer.update")
            except InjectedFaultError as e:
                raise failing.FailedSuggestError(str(e)) from None
        self._inner.update(completed, all_active)

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        try:
            self._chaos.strike("designer.suggest")
        except InjectedFaultError as e:
            raise failing.FailedSuggestError(str(e)) from None
        return list(self._inner.suggest(count))

    # -- cross-study batch protocol (vizier_tpu.compute IR) -----------------
    # Chaos-wrapped designers stay batchable: ``compute_program`` resolves
    # the inner designer's registered DesignerProgram and wraps it in
    # :class:`ChaosProgram`, so fault injection rides the IR generically —
    # every registered program family (exact, sparse, UCB-PE, future
    # designers) inherits slot-isolation chaos without per-designer method
    # copies. A strike in the per-slot host-side hooks (prepare/finalize)
    # degrades only that study; a strike in ``device_program`` poisons the
    # shared device body, driving the whole-batch sequential fallback.

    def compute_program(self, count: Optional[int] = None):
        from vizier_tpu.compute import registry as compute_registry

        resolved = compute_registry.resolve(self._inner, count)
        if resolved is None:
            return None
        program, key = resolved
        return ChaosProgram(program, self), key

    # Legacy duck-typed surface (direct callers and tests).

    def batch_bucket_key(self, count: Optional[int] = None):
        key_fn = getattr(self._inner, "batch_bucket_key", None)
        return key_fn(count) if key_fn is not None else None

    def batch_prepare(self, count: Optional[int] = None) -> dict:
        try:
            self._chaos.strike("designer.batch_prepare")
        except InjectedFaultError as e:
            raise failing.FailedSuggestError(str(e)) from None
        return self._inner.batch_prepare(count)

    def batch_execute(
        self, items, pad_to: Optional[int] = None, placement=None
    ):
        self._chaos.strike("designer.batch_execute")
        if placement is not None:
            return self._inner.batch_execute(
                items, pad_to=pad_to, placement=placement
            )
        return self._inner.batch_execute(items, pad_to=pad_to)

    def batch_finalize(self, item: dict, output) -> List[trial_.TrialSuggestion]:
        try:
            self._chaos.strike("designer.batch_finalize")
        except InjectedFaultError as e:
            raise failing.FailedSuggestError(str(e)) from None
        return self._inner.batch_finalize(item, output)


class ChaosProgram:
    """Fault-injecting wrapper over any compute-IR ``DesignerProgram``.

    The generic chaos slot-isolation hook the compute-IR conformance pass
    requires: wrapping happens at program resolution
    (``ChaosDesigner.compute_program``), so every registered program —
    exact, sparse, UCB-PE, future designers — is chaos-testable through
    one seam. The host-side hooks route through the bound chaos designer's
    striking ``batch_*`` methods (so per-test instance patches keep
    working): a per-slot strike raises designer-shaped
    ``FailedSuggestError`` and degrades only that study; a
    ``device_program`` strike poisons the shared device body, driving the
    executor's whole-batch sequential fallback.
    """

    def __init__(self, inner, chaos_designer: ChaosDesigner):
        self._inner = inner
        self._designer = chaos_designer
        self.kind = inner.kind
        self.device_phase = inner.device_phase
        self.surrogate_family = inner.surrogate_family
        # Mesh shardability is the wrapped program's call: a chaos-wrapped
        # shardable program keeps executing on its assigned placement, so
        # device-failure strikes exercise the mesh dispatch path too.
        self.shardable_batch_axis = getattr(
            inner, "shardable_batch_axis", ""
        )

    def bucket_key(self, designer, count):
        return self._inner.bucket_key(
            getattr(designer, "_inner", designer), count
        )

    def prepare(self, designer, count):
        return designer.batch_prepare(count)

    def device_program(self, items, pad_to: Optional[int] = None, placement=None):
        return self._designer.batch_execute(
            items, pad_to=pad_to, placement=placement
        )

    def finalize(self, designer, item, output):
        return designer.batch_finalize(item, output)

    def prewarm_factory(self, problem, **kwargs):
        return self._inner.prewarm_factory(problem, **kwargs)


def chaos_designer_factory(
    inner_factory: Callable[..., core_lib.Designer],
    chaos: ChaosMonkey,
    **chaos_kwargs: Any,
) -> Callable[..., core_lib.Designer]:
    """Wraps a designer factory so every built designer is chaos-wrapped."""

    def factory(problem, **kwargs):
        return ChaosDesigner(
            inner_factory(problem, **kwargs), chaos, **chaos_kwargs
        )

    return factory


class _ChaosProxy:
    """Injects a fault before delegating the named methods to ``inner``.

    Fail-fast by design: the strike happens BEFORE the delegate runs, so an
    injected fault never leaves a half-applied write behind — chaos tests
    probe the retry/fallback machinery, not datastore crash atomicity.
    """

    _PREFIX = "proxy"

    def __init__(self, inner: Any, chaos: ChaosMonkey, methods: Sequence[str]):
        self._inner = inner
        self._chaos = chaos
        self._methods = frozenset(methods)

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name not in self._methods or not callable(attr):
            return attr

        def wrapped(*args, **kwargs):
            self._chaos.strike(f"{self._PREFIX}.{name}")
            return attr(*args, **kwargs)

        return wrapped


class ChaosDataStore(_ChaosProxy):
    """Fault-injecting wrapper over any ``DataStore`` implementation."""

    _PREFIX = "datastore"

    DEFAULT_METHODS = (
        "get_trial",
        "list_trials",
        "update_trial",
        "create_trial",
        "max_trial_id",
        "load_study",
    )

    def __init__(
        self,
        inner: Any,
        chaos: ChaosMonkey,
        methods: Sequence[str] = DEFAULT_METHODS,
    ):
        super().__init__(inner, chaos, methods)


class ChaosServiceStub(_ChaosProxy):
    """Fault-injecting wrapper over a Vizier service stub / servicer.

    Simulates transport flakiness between client and service; wrap the
    object handed to ``VizierClient`` with it and the client's RetryPolicy
    absorbs the injected ``InjectedFaultError``s.
    """

    _PREFIX = "rpc"

    DEFAULT_METHODS = (
        "SuggestTrials",
        "GetOperation",
        "GetTrial",
        "ListTrials",
        "AddTrialMeasurement",
        "CompleteTrial",
        "GetStudy",
        "ListOptimalTrials",
    )

    def __init__(
        self,
        inner: Any,
        chaos: ChaosMonkey,
        methods: Sequence[str] = DEFAULT_METHODS,
    ):
        super().__init__(inner, chaos, methods)
