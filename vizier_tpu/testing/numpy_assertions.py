"""Nested-structure numpy assertions for tests.

Parity with ``/root/reference/vizier/testing/numpy_assertions.py:23``
(``assert_arraytree_allclose``), extended to arbitrary pytrees (our
params/GPState containers are flax structs, not plain dicts).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import numpy as np


def assert_arraytree_allclose(d1: Mapping[str, Any], d2: Mapping[str, Any], **kwargs) -> None:
    """Compares two (nested) dictionaries of arrays/scalars."""
    np.testing.assert_equal(sorted(d1.keys()), sorted(d2.keys()))
    for k, v in d1.items():
        if isinstance(v, dict):
            assert_arraytree_allclose(v, d2[k], **kwargs)
        else:
            try:
                np.testing.assert_allclose(v, d2[k], err_msg=f"key={k!r}", **kwargs)
            except TypeError:
                np.testing.assert_equal(v, d2[k], err_msg=f"key={k!r}")


def assert_pytree_allclose(t1: Any, t2: Any, **kwargs) -> None:
    """Compares two arbitrary pytrees (same treedef, allclose leaves)."""
    l1, d1 = jax.tree_util.tree_flatten(t1)
    l2, d2 = jax.tree_util.tree_flatten(t2)
    if d1 != d2:
        raise AssertionError(f"Tree structures differ:\n  {d1}\n  {d2}")
    for i, (a, b) in enumerate(zip(l1, l2)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), err_msg=f"leaf {i}", **kwargs
        )
