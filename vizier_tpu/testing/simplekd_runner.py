"""SimpleKD convergence tester.

Parity with
``/root/reference/vizier/_src/algorithms/testing/simplekd_runner.py:32``:
runs a designer on the SimpleKD mixed-space objective and asserts it gets
within ``max_relative_error`` of the known optimum.
"""

from __future__ import annotations

import dataclasses

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.benchmarks.experimenters.synthetic import simplekd
from vizier_tpu.benchmarks.runners import benchmark_runner, benchmark_state
from vizier_tpu.pyvizier import trial as trial_


class ConvergenceTestError(Exception):
    pass


@dataclasses.dataclass
class SimpleKDConvergenceTester:
    best_category: str = "corner"
    num_trials: int = 60
    batch_size: int = 5
    max_abs_error: float = 0.4  # objective units below the optimum (0.0)
    seed: int = 0

    def assert_converges(self, designer_factory: core_lib.DesignerFactory) -> float:
        experimenter = simplekd.SimpleKDExperimenter(self.best_category)
        state = benchmark_state.BenchmarkState.from_designer_factory(
            experimenter, designer_factory, seed=self.seed
        )
        benchmark_runner.BenchmarkRunner(
            [benchmark_runner.GenerateAndEvaluate(self.batch_size)],
            num_repeats=self.num_trials // self.batch_size,
        ).run(state)
        trials = state.algorithm.supporter.GetTrials(
            status_matches=trial_.TrialStatus.COMPLETED
        )
        best = max(
            t.final_measurement.metrics["value"].value
            for t in trials
            if t.final_measurement is not None
        )
        error = experimenter.optimal_value - best
        if error > self.max_abs_error:
            raise ConvergenceTestError(
                f"Best value {best:.4f} is {error:.4f} below the optimum "
                f"(allowed {self.max_abs_error})."
            )
        return best
