"""Smoke assertions for gradient-free acquisition optimizers.

Parity with
``/root/reference/vizier/_src/algorithms/testing/optimizer_test_utils.py:26,51``
as plain pytest-style functions: optimize a random score over a search
space and assert suggestions are produced and contained in the space.
"""

from __future__ import annotations

import numpy as np

from vizier_tpu.optimizers import base as optimizer_base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc


def assert_passes_on_random_single_metric_function(
    search_space: pc.SearchSpace,
    optimizer: optimizer_base.GradientFreeOptimizer,
    *,
    np_random_seed: int,
    count: int = 5,
) -> None:
    """Optimizer produces in-space suggestions for a random single objective."""
    rng = np.random.default_rng(np_random_seed)
    problem = base_study_config.ProblemStatement(search_space=search_space)
    problem.metric_information.append(
        base_study_config.MetricInformation(
            name="acquisition", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
        )
    )

    def mock_score(trials):
        return {"acquisition": rng.uniform(size=[len(trials), 1])}

    suggestions = optimizer.optimize(mock_score, problem, count=count)
    assert suggestions, "optimizer returned no suggestions"
    for suggestion in suggestions:
        search_space.assert_contains(suggestion.parameters)


def assert_passes_on_random_multi_metric_function(
    search_space: pc.SearchSpace,
    optimizer: optimizer_base.GradientFreeOptimizer,
    *,
    np_random_seed: int,
    count: int = 5,
) -> None:
    """Same, with a random bi-objective score."""
    rng = np.random.default_rng(np_random_seed)
    problem = base_study_config.ProblemStatement(search_space=search_space)
    for name in ("acquisition_1", "acquisition_2"):
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name=name, goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )

    def mock_score(trials):
        return {
            "acquisition_1": rng.uniform(size=[len(trials), 1]),
            "acquisition_2": rng.uniform(size=[len(trials), 1]),
        }

    suggestions = optimizer.optimize(mock_score, problem, count=count)
    assert suggestions, "optimizer returned no suggestions"
    for suggestion in suggestions:
        search_space.assert_contains(suggestion.parameters)
