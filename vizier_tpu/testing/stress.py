"""Shared multi-client service-stress topology.

One implementation of the reference ``performance_test.py:44-89`` load
shape — a 2-D RANDOM_SEARCH study, N thread-pool clients each running
their own suggest→complete loop — used by both the CI stress test
(``tests/service/test_performance.py``) and the throughput measurement
tool (``tools/service_throughput.py``) so the two cannot drift apart.
"""

from __future__ import annotations

import concurrent.futures as cf
import time
from typing import List, Tuple

from vizier_tpu import pyvizier as vz
from vizier_tpu.service import clients as clients_lib


def stress_study_config() -> vz.StudyConfig:
    sc = vz.StudyConfig()
    sc.search_space.root.add_float_param("x", 0.0, 1.0)
    sc.search_space.root.add_float_param("y", 0.0, 1.0)
    sc.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MINIMIZE)
    )
    sc.algorithm = "RANDOM_SEARCH"
    return sc


def run_stress_round(
    study: "clients_lib.Study", num_clients: int, trials_each: int
) -> Tuple[float, int, List[List[int]]]:
    """Runs the N-client suggest→complete round.

    Returns ``(wall_s, completed, per_worker_trial_ids)``: ``completed``
    counts COMPLETED trials only (an ACTIVE row left behind by a dropped
    completion must not pass for throughput), and the per-worker id lists
    let callers assert cross-worker trial disjointness.
    """

    def worker(worker_id: int) -> List[int]:
        my_ids: List[int] = []
        for _ in range(trials_each):
            (trial,) = study.suggest(count=1, client_id=f"worker_{worker_id}")
            x, y = float(trial.parameters["x"]), float(trial.parameters["y"])
            trial.complete(
                vz.Measurement(metrics={"obj": (x - 0.3) ** 2 + (y - 0.7) ** 2})
            )
            my_ids.append(trial.id)
        return my_ids

    t0 = time.perf_counter()
    with cf.ThreadPoolExecutor(max_workers=num_clients) as pool:
        per_worker = list(pool.map(worker, range(num_clients)))
    wall = time.perf_counter() - t0
    completed = len(
        list(study.trials(vz.TrialFilter(status=[vz.TrialStatus.COMPLETED])))
    )
    return wall, completed, per_worker
