"""Seeded NETWORK fault injection for the cross-process tier.

``testing/chaos.py`` injects faults at stack seams (designer, datastore,
service stub); this module injects them at the **links**: every wrapped
call is attributed to a directed ``src -> dst`` edge of the fleet graph,
and a per-link schedule decides whether the call is dropped (raises
transport-shaped), delayed (a slow link, NOT a dead one — the case lease
detection must tolerate), duplicated (the at-least-once delivery the
replication protocol's seq filtering must absorb), or partitioned away
entirely.

- :class:`NetChaos` — the seeded schedule + RNG. ``set_link`` installs
  probabilistic drop/delay/duplicate rules (``*`` wildcards match any
  node); ``partition(node)``/``heal(node)`` atomically isolate/rejoin a
  node (every link touching it fails), ``partition_link`` severs one
  directed edge. All draws come from ONE ``random.Random(seed)`` behind
  a leaf lock, and every strike draws exactly three variates, so a
  single-threaded run is exactly reproducible regardless of which
  probabilities are zero — the same determinism contract as
  ``ChaosMonkey``, with which it composes (wrap one proxy around the
  other; they draw from independent streams).
- :meth:`NetChaos.wrap` / :meth:`NetChaos.wrap_stub` — callable/stub
  proxies that strike before delegating. Drops and partitions raise
  ``ConnectionError`` subclasses, so the reliability layer classifies
  them transient and the routed stub's failure hook sees a transport
  fault — injected faults travel the exact production failure path.
- :meth:`NetChaos.from_spec` — parses the ``VIZIER_NETCHAOS`` string
  (``seed=7;drop=a>b:0.1;delay=a>*:0.05@0.3;dup=a>b:0.02;partition=c``),
  which is how a subprocess replica arms fault injection on its own
  outbound replication links (``replica_main`` hands the parsed schedule
  to its ``GrpcReplicationLink``, which strikes the ``replica_id ->
  successor`` link before every delivery attempt).

Fail-fast by design: strikes happen BEFORE the delegate runs, so a
dropped call never leaves a half-applied write behind; a *duplicated*
call runs the delegate twice and returns the second outcome (at-least-
once delivery — receivers must deduplicate, which the standby store's
sequence filtering and the WAL's tolerant replay both do).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple


class NetChaosError(ConnectionError):
    """An injected network fault (transport-shaped: classified transient)."""


class LinkDroppedError(NetChaosError):
    """The link's schedule dropped this call."""


class PartitionedError(NetChaosError):
    """The link is inside a partition window."""


class LinkRule:
    """One directed link's fault schedule."""

    def __init__(
        self,
        *,
        drop_prob: float = 0.0,
        delay_prob: float = 0.0,
        delay_secs: float = 0.0,
        duplicate_prob: float = 0.0,
    ):
        for name, p in (
            ("drop_prob", drop_prob),
            ("delay_prob", delay_prob),
            ("duplicate_prob", duplicate_prob),
        ):
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {p}")
        self.drop_prob = drop_prob
        self.delay_prob = delay_prob
        self.delay_secs = delay_secs
        self.duplicate_prob = duplicate_prob


class NetChaos:
    """Seeded per-link drop/delay/duplicate/partition injection."""

    def __init__(
        self,
        seed: int = 0,
        *,
        sleep_fn: Callable[[float], None] = time.sleep,
    ):
        self.seed = seed
        self._sleep_fn = sleep_fn
        self._rng = random.Random(seed)
        # Leaf lock: RNG draws, rule/partition tables, counters only.
        self._lock = threading.Lock()
        self._rules: Dict[Tuple[str, str], LinkRule] = {}
        self._partitioned_nodes: set = set()
        self._partitioned_links: set = set()
        # "src>dst" -> {"calls", "drops", "delays", "duplicates",
        # "partitioned"}
        self._counts: Dict[str, Dict[str, int]] = {}

    # -- schedule ------------------------------------------------------------

    def set_link(
        self,
        src: str,
        dst: str,
        *,
        drop_prob: float = 0.0,
        delay_prob: float = 0.0,
        delay_secs: float = 0.0,
        duplicate_prob: float = 0.0,
    ) -> None:
        """Installs (or replaces) the rule for ``src -> dst``; ``*``
        matches any node (exact beats ``src>*`` beats ``*>dst`` beats
        ``*>*``)."""
        rule = LinkRule(
            drop_prob=drop_prob,
            delay_prob=delay_prob,
            delay_secs=delay_secs,
            duplicate_prob=duplicate_prob,
        )
        with self._lock:
            self._rules[(src, dst)] = rule

    def clear_link(self, src: str, dst: str) -> None:
        with self._lock:
            self._rules.pop((src, dst), None)

    def partition(self, *nodes: str) -> None:
        """Isolates ``nodes``: every link touching any of them fails with
        :class:`PartitionedError` until :meth:`heal`."""
        with self._lock:
            self._partitioned_nodes.update(nodes)

    def heal(self, *nodes: str) -> None:
        """Rejoins ``nodes`` (and clears directed partitions touching
        them)."""
        with self._lock:
            self._partitioned_nodes.difference_update(nodes)
            self._partitioned_links = {
                (s, d)
                for s, d in self._partitioned_links
                if s not in nodes and d not in nodes
            }

    def partition_link(self, src: str, dst: str) -> None:
        """Severs ONE directed edge (asymmetric partitions: a can reach b
        while b cannot reach a)."""
        with self._lock:
            self._partitioned_links.add((src, dst))

    def heal_link(self, src: str, dst: str) -> None:
        with self._lock:
            self._partitioned_links.discard((src, dst))

    def is_partitioned(self, src: str, dst: str) -> bool:
        with self._lock:
            return self._is_partitioned_locked(src, dst)

    def _is_partitioned_locked(self, src: str, dst: str) -> bool:
        return (
            src in self._partitioned_nodes
            or dst in self._partitioned_nodes
            or (src, dst) in self._partitioned_links
        )

    def _rule_for(self, src: str, dst: str) -> Optional[LinkRule]:
        for key in ((src, dst), (src, "*"), ("*", dst), ("*", "*")):
            rule = self._rules.get(key)
            if rule is not None:
                return rule
        return None

    # -- injection -----------------------------------------------------------

    def strike(self, src: str, dst: str) -> bool:
        """One send over ``src -> dst``: maybe partitioned, dropped, or
        delayed; returns True when the call must be DUPLICATED.

        Always draws exactly three variates per call, so the fault
        sequence is a pure function of (seed, call index) — independent
        of which probabilities are zero.
        """
        site = f"{src}>{dst}"
        with self._lock:
            counts = self._counts.setdefault(
                site,
                {
                    "calls": 0,
                    "drops": 0,
                    "delays": 0,
                    "duplicates": 0,
                    "partitioned": 0,
                },
            )
            counts["calls"] += 1
            rule = self._rule_for(src, dst)
            drop = self._rng.random() < (rule.drop_prob if rule else 0.0)
            lag = self._rng.random() < (rule.delay_prob if rule else 0.0)
            dup = self._rng.random() < (
                rule.duplicate_prob if rule else 0.0
            )
            if self._is_partitioned_locked(src, dst):
                counts["partitioned"] += 1
                raise PartitionedError(
                    f"netchaos: link {site} is partitioned"
                )
            if drop:
                counts["drops"] += 1
            if lag:
                counts["delays"] += 1
            if dup:
                counts["duplicates"] += 1
            delay_secs = rule.delay_secs if (rule and lag) else 0.0
        if delay_secs > 0:
            self._sleep_fn(delay_secs)
        if drop:
            raise LinkDroppedError(f"netchaos: dropped on link {site}")
        return dup

    def wrap(self, fn: Callable, src: str, dst: str) -> Callable:
        """Wraps one callable as traffic on ``src -> dst``."""

        def wrapped(*args, **kwargs):
            duplicate = self.strike(src, dst)
            if duplicate:
                # At-least-once delivery: run the delegate twice; the
                # first outcome (result OR error) is discarded — the wire
                # only promises the SECOND copy's fate to the caller.
                try:
                    fn(*args, **kwargs)
                except Exception:
                    pass
            return fn(*args, **kwargs)

        return wrapped

    def wrap_stub(
        self,
        stub: Any,
        src: str,
        dst: str,
        methods: Optional[Sequence[str]] = None,
    ) -> "_NetChaosStub":
        """Proxies a service stub so each listed RPC (default: every
        public callable) rides the ``src -> dst`` link."""
        return _NetChaosStub(stub, self, src, dst, methods)

    # -- accounting ----------------------------------------------------------

    def counts(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {site: dict(c) for site, c in self._counts.items()}

    def total(self, field: str) -> int:
        with self._lock:
            return sum(c.get(field, 0) for c in self._counts.values())

    # -- env-spec parsing ----------------------------------------------------

    @classmethod
    def from_spec(cls, spec: str) -> "NetChaos":
        """Parses a ``VIZIER_NETCHAOS`` schedule string.

        Semicolon-separated directives::

            seed=7                      # RNG seed (default 0)
            drop=src>dst:0.1            # drop probability on one link
            delay=src>dst:0.05@0.3      # 50 ms delay at probability 0.3
                                        # (@prob optional, default 1.0)
            dup=src>dst:0.02            # duplicate probability
            partition=node              # isolate a node
            partition=src>dst           # sever one directed edge

        ``*`` wildcards match any node on either side.
        """
        net = cls()
        directives = [d.strip() for d in spec.split(";") if d.strip()]
        pending: Dict[Tuple[str, str], Dict[str, float]] = {}
        partitions = []
        for directive in directives:
            key, _, value = directive.partition("=")
            key, value = key.strip(), value.strip()
            if not value:
                raise ValueError(f"Bad netchaos directive: {directive!r}")
            if key == "seed":
                net = cls(seed=int(value))
            elif key == "partition":
                partitions.append(value)
            elif key in ("drop", "delay", "dup"):
                link_part, _, prob_part = value.partition(":")
                src, sep, dst = link_part.partition(">")
                if not sep or not prob_part:
                    raise ValueError(
                        f"Bad netchaos directive: {directive!r} "
                        "(expected key=src>dst:value)"
                    )
                rule = pending.setdefault((src, dst), {})
                if key == "drop":
                    rule["drop_prob"] = float(prob_part)
                elif key == "dup":
                    rule["duplicate_prob"] = float(prob_part)
                else:
                    secs, _, prob = prob_part.partition("@")
                    rule["delay_secs"] = float(secs)
                    rule["delay_prob"] = float(prob) if prob else 1.0
            else:
                raise ValueError(f"Unknown netchaos directive: {key!r}")
        for (src, dst), kwargs in pending.items():
            net.set_link(src, dst, **kwargs)
        for value in partitions:
            src, sep, dst = value.partition(">")
            if sep:
                net.partition_link(src, dst)
            else:
                net.partition(value)
        return net


class _NetChaosStub:
    """Stub proxy routing each RPC through one link's schedule."""

    def __init__(
        self,
        inner: Any,
        net: NetChaos,
        src: str,
        dst: str,
        methods: Optional[Sequence[str]],
    ):
        self._inner = inner
        self._net = net
        self._src = src
        self._dst = dst
        self._methods = frozenset(methods) if methods is not None else None

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name.startswith("_") or not callable(attr):
            return attr
        if self._methods is not None and name not in self._methods:
            return attr
        return self._net.wrap(attr, self._src, self._dst)
