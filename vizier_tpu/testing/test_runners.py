"""Designer smoke-test runners.

Parity with ``/root/reference/vizier/_src/algorithms/testing/test_runners.py:32``:
drive a designer through suggest/update loops with random metrics, asserting
every suggestion stays inside the search space.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class RandomMetricsRunner:
    """Feeds random metric values to a designer for N iterations."""

    problem: base_study_config.ProblemStatement
    iters: int = 5
    batch_size: int = 1
    seed: int = 0
    verify_parameters: bool = True

    def run_designer(self, designer: core_lib.Designer) -> List[trial_.Trial]:
        rng = np.random.default_rng(self.seed)
        all_trials: List[trial_.Trial] = []
        next_id = 1
        for _ in range(self.iters):
            suggestions = designer.suggest(self.batch_size)
            if not suggestions:
                break
            completed = []
            for s in suggestions:
                if self.verify_parameters:
                    self.problem.search_space.assert_contains(s.parameters)
                t = s.to_trial(next_id)
                next_id += 1
                metrics = {
                    m.name: float(rng.uniform(-1, 1))
                    for m in self.problem.metric_information
                }
                t.complete(trial_.Measurement(metrics=metrics))
                completed.append(t)
            all_trials.extend(completed)
            designer.update(
                core_lib.CompletedTrials(completed), core_lib.ActiveTrials()
            )
        return all_trials
