"""Canonical search spaces and metric configs shared by tests.

Parity with ``/root/reference/vizier/testing/test_studies.py:24-177``.
"""

from __future__ import annotations

from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc

MetricInformation = base_study_config.MetricInformation
ObjectiveMetricGoal = base_study_config.ObjectiveMetricGoal


def flat_space_with_all_types() -> pc.SearchSpace:
    """One of each parameter type, mixed scalings."""
    space = pc.SearchSpace()
    root = space.root
    root.add_float_param("lineardouble", -1.0, 2.0)
    root.add_float_param("logdouble", 1e-4, 1e2, scale_type=pc.ScaleType.LOG)
    root.add_int_param("integer", -2, 2)
    root.add_categorical_param("categorical", ["a", "aa", "aaa"])
    root.add_bool_param("boolean")
    root.add_discrete_param("discrete_double", [-0.5, 1.0, 1.2])
    root.add_discrete_param("discrete_logdouble", [1e-5, 1e-2, 1e-1])
    root.add_discrete_param("discrete_int", [-1, 1, 2])
    return space


def flat_continuous_space_with_scaling() -> pc.SearchSpace:
    space = pc.SearchSpace()
    root = space.root
    root.add_float_param("double", -1.0, 2.0)
    root.add_float_param("logdouble", 1e-4, 1e2, scale_type=pc.ScaleType.LOG)
    root.add_float_param("reverselogdouble", 0.1, 1.0, scale_type=pc.ScaleType.REVERSE_LOG)
    return space


def conditional_automl_space() -> pc.SearchSpace:
    """The classic conditional AutoML space: model type gates child params."""
    space = pc.SearchSpace()
    root = space.root
    model = root.add_categorical_param("model_type", ["linear", "dnn"])
    dnn = model.select_values(["dnn"])
    dnn.add_float_param("learning_rate", 0.0001, 1.0, scale_type=pc.ScaleType.LOG)
    linear = space.select("model_type").select_values(["linear"])
    linear.add_float_param("l2_reg", 1e-6, 1.0, scale_type=pc.ScaleType.LOG)
    return space


def metrics_objective_maximize() -> base_study_config.MetricsConfig:
    return base_study_config.MetricsConfig(
        [MetricInformation(name="objective", goal=ObjectiveMetricGoal.MAXIMIZE)]
    )


def metrics_multiobjective() -> base_study_config.MetricsConfig:
    return base_study_config.MetricsConfig(
        [
            MetricInformation(name="obj1", goal=ObjectiveMetricGoal.MAXIMIZE),
            MetricInformation(name="obj2", goal=ObjectiveMetricGoal.MINIMIZE),
        ]
    )


def metrics_with_safety() -> base_study_config.MetricsConfig:
    return base_study_config.MetricsConfig(
        [
            MetricInformation(name="objective", goal=ObjectiveMetricGoal.MAXIMIZE),
            MetricInformation(
                name="safety", goal=ObjectiveMetricGoal.MAXIMIZE, safety_threshold=0.2
            ),
        ]
    )
