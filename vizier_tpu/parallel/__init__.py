"""Device-mesh sharding: the ICI data plane the reference never had.

The reference is CPU-single-host inside each Pythia call (SURVEY.md §2.10,
§5.8); here the three embarrassingly-parallel axes of the GP-bandit suggest
path shard across a ``jax.sharding.Mesh``:

- **restarts** — ARD L-BFGS random restarts (data-parallel over devices);
- **ensemble** — GP hyperparameter ensemble members;
- **pools** — independent Eagle pools of the acquisition sweep (each device
  runs its own ask-evaluate-tell loop; results merge with one final top-k).

All three are batch axes of already-vmapped jitted programs, so sharding is
pure ``NamedSharding`` annotation — XLA partitions the programs and inserts
any collectives over ICI. Gradients/Cholesky stay device-local: zero
communication inside the hot loops, one gather at the end.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from vizier_tpu.designers.gp import acquisitions
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.optimizers import vectorized as vectorized_lib

# Cross-study continuous batching (the intra-host sibling of the mesh data
# plane below): N same-shape-bucket studies per device dispatch.
from vizier_tpu.parallel.batch_executor import BatchExecutor
from vizier_tpu.parallel.batch_executor import BatchSlotError
from vizier_tpu.parallel.batch_executor import BucketKey

# Mesh execution plane for the batch executor (VIZIER_MESH*): device
# placements, shard-granularity padding, and the multi-host coordinator
# seam.
from vizier_tpu.parallel.mesh import DevicePlacement
from vizier_tpu.parallel.mesh import MeshConfig
from vizier_tpu.parallel.mesh import build_placements
from vizier_tpu.parallel.mesh import multihost_mesh

Array = jax.Array

DEVICE_AXIS = "devices"


def create_mesh(
    n_devices: Optional[int] = None, axis_name: str = DEVICE_AXIS
) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` (default: all) devices."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"Requested {n_devices} devices but only {len(devices)} exist."
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def _distributed_initialized() -> bool:
    """Whether the jax distributed runtime is already up.

    ``jax.distributed.is_initialized`` only exists on newer jax; on
    releases without it (0.4.37 ships only initialize/shutdown) the
    coordination client on the private global state carries the same bit.
    Neither path touches devices, so the backend stays uninitialized.
    """
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed as _distributed_src

        return _distributed_src.global_state.client is not None
    except Exception:
        return False


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Mesh:
    """Joins a multi-host JAX cluster and returns the global device mesh.

    The reference's distributed story is gRPC-only (one CPU host per
    Pythia call); this is the scale-out path it never had: each host runs
    one process, ``jax.distributed.initialize`` wires the cluster over
    DCN, and the returned 1-D mesh spans every chip of every host. All
    sharded entry points in this module take that mesh unchanged — the
    parallel axes (restarts / ensemble / pools) are communication-free,
    so cross-host traffic is one final top-k gather; everything else
    rides ICI within each host's slice.

    On TPU pods the arguments are auto-detected from the runtime
    environment and may be omitted.

    MUST run before any JAX call that initializes the XLA backend
    (including ``jax.devices()``): ``jax.distributed.initialize`` refuses
    to run afterwards. Initialization state is checked via
    ``_distributed_initialized`` — never by touching devices.
    """
    if not _distributed_initialized():
        if coordinator_address is not None:
            # Explicit cluster spec: failures must propagate — a silently
            # absent cluster would shard per-host and corrupt results.
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        else:
            try:
                jax.distributed.initialize()  # TPU-pod auto-detection
            except Exception:
                pass  # plain single host: fall through to a local mesh
    return create_mesh()


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh):
    """Leading-axis sharding over the device axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


# ---------------------------------------------------------------------------
# Sharded ARD training: restarts across devices.
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("model", "optimizer", "num_restarts", "ensemble_size", "mesh"),
)
def train_gp_sharded(
    model: gp_lib.VizierGaussianProcess,
    optimizer: lbfgs_lib.Optimizer,
    data: gp_lib.GPData,
    rng: Array,
    num_restarts: int,
    ensemble_size: int,
    mesh: Mesh,
    warm_start: Optional[dict] = None,
) -> gp_lib.GPState:
    """Multi-restart ARD with the restart axis sharded over the mesh.

    ``num_restarts`` should be a multiple of the mesh size. Data is
    replicated (it is small); each device runs its restarts locally; the
    final top-k selection is the only cross-device reduction. ``warm_start``
    replaces the first restart here — unlike ``gp_bandit._train_gp``, which
    prepends it as an extra row — because appending would break the
    restarts-divisible-by-mesh sharding; at mesh-scale restart budgets the
    one lost random init is immaterial.
    """
    coll = model.param_collection()
    inits = coll.batch_random_init_unconstrained(rng, num_restarts)
    if warm_start is not None:
        inits = jax.tree_util.tree_map(
            lambda batch, warm: batch.at[0].set(warm), inits, warm_start
        )
    inits = jax.lax.with_sharding_constraint(
        inits, batch_sharded(mesh)
    )
    data = jax.lax.with_sharding_constraint(data, replicated(mesh))
    loss_fn = lambda p: model.neg_log_likelihood(p, data)
    result = optimizer(loss_fn, inits, best_n=ensemble_size)
    return jax.vmap(lambda p: model.precompute(p, data))(result.params)


# ---------------------------------------------------------------------------
# Sharded acquisition sweep: independent eagle pools per device.
# ---------------------------------------------------------------------------


def maximize_score_fn_sharded(
    vec_opt: vectorized_lib.VectorizedOptimizer,
    score_fn,
    rng: Array,
    count: int,
    num_pools: int,
    mesh: Mesh,
    prior_features: Optional[kernels.MixedFeatures] = None,
) -> vectorized_lib.VectorizedOptimizerResult:
    """Runs ``num_pools`` independent vectorized sweeps, pools sharded.

    Each pool consumes ``vec_opt.max_evaluations`` scores; total work is
    ``num_pools ×`` that, wall-clock ≈ one pool when num_pools == mesh size.
    The merge is a single global top-k. Traceable (callable from inside
    larger jitted programs, e.g. the UCB-PE batch loop).
    """
    keys = jax.random.split(rng, num_pools)
    keys = jax.lax.with_sharding_constraint(keys, batch_sharded(mesh))

    def run_pool(key: Array) -> vectorized_lib.VectorizedOptimizerResult:
        return vec_opt(score_fn, key, count=count, prior_features=prior_features)

    results = jax.vmap(run_pool)(keys)  # [pools, count, ...]
    flat = num_pools * count  # explicit: -1 breaks on zero-width categorical
    flat_scores = results.scores.reshape(flat)
    flat_cont = results.features.continuous.reshape(
        (flat,) + results.features.continuous.shape[2:]
    )
    flat_cat = results.features.categorical.reshape(
        (flat,) + results.features.categorical.shape[2:]
    )
    top_scores, idx = jax.lax.top_k(flat_scores, count)
    return vectorized_lib.VectorizedOptimizerResult(
        kernels.MixedFeatures(flat_cont[idx], flat_cat[idx]), top_scores
    )


@functools.partial(
    jax.jit, static_argnames=("vec_opt", "count", "num_pools", "mesh")
)
def maximize_acquisition_sharded(
    vec_opt: vectorized_lib.VectorizedOptimizer,
    scoring: acquisitions.ScoringFunction,
    rng: Array,
    count: int,
    num_pools: int,
    mesh: Mesh,
    prior_features: Optional[kernels.MixedFeatures] = None,
) -> vectorized_lib.VectorizedOptimizerResult:
    """Pool-sharded sweep of a ScoringFunction pytree (jitted entry point)."""
    scoring = jax.lax.with_sharding_constraint(scoring, replicated(mesh))
    return maximize_score_fn_sharded(
        vec_opt, scoring.score, rng, count, num_pools, mesh, prior_features
    )


# ---------------------------------------------------------------------------
# One fused multi-chip "suggest step" (ARD train + acquisition sweep).
# ---------------------------------------------------------------------------


def suggest_step_sharded(
    model: gp_lib.VizierGaussianProcess,
    optimizer: lbfgs_lib.Optimizer,
    vec_opt: vectorized_lib.VectorizedOptimizer,
    data: gp_lib.GPData,
    rng: Array,
    *,
    count: int,
    num_restarts: int,
    ensemble_size: int,
    mesh: Mesh,
    ucb_coefficient: float = 1.8,
) -> vectorized_lib.VectorizedOptimizerResult:
    """Full GP-bandit compute step over the mesh: train → score → sweep."""
    train_rng, acq_rng = jax.random.split(rng)
    states = train_gp_sharded(
        model, optimizer, data, train_rng, num_restarts, ensemble_size, mesh
    )
    predictive = gp_lib.EnsemblePredictive(states)
    best_label = jnp.max(jnp.where(data.row_mask, data.labels, -jnp.inf))
    scoring = acquisitions.ScoringFunction(
        predictive=predictive,
        acquisition=acquisitions.UCB(ucb_coefficient),
        best_label=best_label,
        trust_region=acquisitions.TrustRegion.from_data(data),
    )
    return maximize_acquisition_sharded(
        vec_opt, scoring, acq_rng, count, len(mesh.devices.flat), mesh
    )
