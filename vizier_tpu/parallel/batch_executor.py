"""Cross-study continuous batching: N same-shape studies, ONE device program.

The LLM-inference-server pattern applied to suggestion serving. Every
study's GP-bandit computation is a small same-shape program — the padding
schedule (``converters.padding``) quantizes trials/features into a small
grid of ``(pad_trials, cont_width, cat_width)`` buckets by construction —
so concurrent designer computations from *different* studies can be
collected into shape-bucket queues and executed as one ``jax.vmap``-ed
dispatch over a leading study axis (``gp_bandit.train_batched`` /
``suggest_batched``). That replaces N dispatches that each leave the MXU
idle between kernel launches with one dispatch of N-fold work.

Scheduling is a bounded micro-batch window: a bucket flushes when it
reaches ``max_batch_size`` slots ("full") or when its oldest slot has
waited ``max_wait_ms`` ("timeout"), so single-study latency is bounded by
the window. Partial batches are padded to ``max_batch_size`` with copies
of slot 0 that are dropped at demux — one compiled program shape per
bucket regardless of occupancy. A batch of one takes the ordinary
sequential designer path (bit-identical to batching off when there is no
concurrency).

Fail isolation: a slot whose host-side ``batch_prepare`` raises is dropped
from the batch before the device program runs; a device-program failure
falls every slot back to its own sequential ``suggest`` (errors stay
per-slot); a slot whose decoded suggestions contain non-finite parameters
gets a typed ``TRANSIENT:`` error. In all three cases the error surfaces
only to that study's waiter, which hands it to the existing reliability
path (retry / circuit breaker / quasi-random fallback) — batchmates are
never poisoned.

Mesh execution plane (``parallel.mesh``, opt-in ``VIZIER_MESH=1``): the
process's devices are carved into placements (1-D submeshes); each bucket
is sticky-assigned to one placement and DIFFERENT buckets execute
concurrently on per-placement worker threads instead of serializing
through the scheduler (which keeps sole ownership of flush *forming* —
windows, lanes, ordering). A flush dispatched to a multi-device placement
is sharded over its study axis (``DevicePlacement.shard``) so one fused
program spans the placement's devices, and every placement pads flushes
at shard granularity (``DevicePlacement.pad_to``: the next power-of-two
multiple of its device count) instead of the single-device executor's
flat pad-to-``max_batch_size`` — a low-occupancy flush no longer computes
``max_batch_size`` padded slots. ``VIZIER_MESH=0`` (default) never builds
placements: single scheduler thread, one device, bit-identical seed path.

Priority lanes (N-lane): every slot rides a named :class:`LaneSpec` lane.
The default table has two — ``live`` (priority 0) and ``speculative``
(priority 1, deferrable): slots submitted with ``speculative=True`` (the
serving tier's background pre-compute, ``vizier_tpu.serving.speculative``)
ride a live flush that is forming anyway, but a bucket holding ONLY
deferrable-lane slots waits for the idle window — it never becomes due
while a lower-priority-number slot is queued in any bucket (bounded by the
lane's ``starvation_cap_ms`` so a live request coalesced onto an in-flight
speculative compute cannot starve), and due batches execute in lane-
priority order. New QoS classes are one more LaneSpec, not a scheduler
rewrite. ``queue_depth()`` / ``live_pending()`` expose per-lane occupancy
so the speculative admission gate can refuse to enqueue under live
saturation.

Weighted fair share (opt-in via the admission controller,
``VIZIER_ADMISSION=1``): inside the live lane, slots carry the tenant the
admission gate admitted (``serving.admission.current_tenant()``), and when
a bucket holds more queued work than one flush, deficit-round-robin
selection across tenants — quantum = the tenant's configured weight —
decides who flushes first instead of FIFO, so a hot tenant cannot
monopolize flush slots: a continuously-hot tenant can delay a light
tenant's first slot by at most one DRR round (the sum of the other
tenants' quanta). Due same-priority batches are likewise ordered by
weighted served-slot counts across buckets. With admission off (the
default) no tenant is attached and selection is exactly the seed FIFO —
bit-identical scheduling.

Batchable designers implement ONE :class:`~vizier_tpu.compute.ir.
DesignerProgram` (bucket_key / prepare / device_program / finalize),
registered in :mod:`vizier_tpu.compute.registry`; the executor resolves a
designer's program there and consumes it generically — the same registry
feeds the prewarm walker, chaos slot-isolation wrappers, the
``vizier_jax_phase_seconds`` device phases, and the speculative lane.
Designers carrying only the legacy duck-typed ``batch_*`` methods (test
stubs, out-of-tree extensions) resolve to an adapter; anything else runs
sequentially.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

from vizier_tpu.compute import ir as compute_ir
from vizier_tpu.compute import registry as compute_registry
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.reliability import errors as errors_lib

# Canonical home is the compute IR (vizier_tpu.compute.ir.BucketKey);
# re-exported here for the executor's existing import surface.
BucketKey = compute_ir.BucketKey


class BatchSlotError(errors_lib.TransientError):
    """A batched slot produced an invalid result (isolated to its study)."""


@dataclasses.dataclass(frozen=True)
class LaneSpec:
    """One QoS lane in the executor's N-lane scheduler.

    ``priority`` orders execution (lower number first). A ``deferrable``
    lane's buckets wait for the idle window — they only become due while
    no strictly-lower-priority slot is queued anywhere — except after
    ``starvation_cap_ms``, the bounded-starvation escape hatch (0 = the
    normal flush window applies even while deferring, i.e. never extend
    the wait).
    """

    name: str
    priority: int
    deferrable: bool = False
    starvation_cap_ms: float = 0.0


LANE_LIVE = "live"
LANE_SPECULATIVE = "speculative"


def default_lanes(speculative_max_wait_ms: float) -> Tuple[LaneSpec, ...]:
    """The seed two-lane table: live traffic plus the deferrable
    speculative pre-compute lane (its starvation cap bounds how long a
    live request coalesced onto an in-flight speculative compute waits)."""
    return (
        LaneSpec(LANE_LIVE, priority=0),
        LaneSpec(
            LANE_SPECULATIVE,
            priority=1,
            deferrable=True,
            starvation_cap_ms=speculative_max_wait_ms,
        ),
    )


class _Slot:
    """One study's pending computation inside a bucket queue.

    ``action`` is the scheduler's verdict, executed by the WAITING thread
    once ``event`` fires: "batched" (finalize ``output``), "sequential"
    (run the plain per-study suggest — the B=1 path, bit-identical to
    batching off), or "fallback" (the shared device program failed; run the
    plain suggest and account it). Host-side prepare/finalize running on
    the waiter threads keeps the scheduler thread free to dispatch the next
    bucket while this one decodes — the continuous-batching pipeline.
    """

    __slots__ = (
        "designer", "program", "count", "enqueued_at", "event", "error",
        "item", "output", "action", "span", "lane", "tenant",
    )

    def __init__(
        self, designer: Any, program: Any, count: int, now: float, span,
        lane: str = LANE_LIVE, tenant: Optional[str] = None,
    ) -> None:
        self.designer = designer
        self.program = program  # the resolved compute-IR DesignerProgram
        self.count = count
        self.enqueued_at = now
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        self.item: Optional[dict] = None
        self.output: Any = None
        self.action: str = "sequential"
        self.span = span  # the submitter's active span (may be None)
        # QoS lane (LaneSpec.name): a deferrable-lane slot may ride a
        # higher-priority flush that is forming anyway, but a bucket
        # holding ONLY deferrable slots defers to queued priority traffic.
        self.lane = lane
        # Fair-share identity (admission on only): who this computation
        # bills to inside the live lane's deficit-round-robin.
        self.tenant = tenant

    @property
    def speculative(self) -> bool:
        return self.lane == LANE_SPECULATIVE


def stack_pytrees(trees: Sequence[Any], pad_to: Optional[int] = None) -> Any:
    """Stacks per-study pytrees along a new leading axis, padding with
    copies of tree 0 up to ``pad_to`` (masked out again at demux).

    Host (numpy) leaves stack in numpy — zero device dispatches; the whole
    batch then crosses to the device once, at the jitted program's entry.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    trees = list(trees)
    if pad_to is not None and pad_to > len(trees):
        trees = trees + [trees[0]] * (pad_to - len(trees))

    def stack(*xs):
        if all(not isinstance(x, jax.Array) for x in xs):
            return np.stack([np.asarray(x) for x in xs])
        return jnp.stack(xs)

    return jax.tree_util.tree_map(stack, *trees)


def place_batch(tree: Any, placement: Optional[Any] = None) -> Any:
    """Commits a stacked (leading-study-axis) flush pytree onto a mesh
    placement's submesh; a no-op when ``placement`` is None (the
    single-device path keeps its lazy host->device copy at jit entry).

    The shardable programs' ``device_program`` bodies route every stacked
    input through this, so intra-flush sharding is one call site per
    program instead of per-leaf plumbing.
    """
    if placement is None:
        return tree
    return placement.shard(tree)


def slice_pytree(tree: Any, index: int) -> Any:
    """Slot ``index`` of a leading-study-axis pytree.

    Demux is meant to run on a host (``jax.device_get``-fetched) tree, where
    each slice is a free numpy view; on device arrays every leaf slice would
    be its own dispatch — fetch once, then slice.
    """
    import jax

    return jax.tree_util.tree_map(lambda a: a[index], tree)


def check_finite_suggestions(suggestions: Sequence[Any], study: str = "") -> None:
    """Raises :class:`BatchSlotError` if any numeric parameter is non-finite.

    A NaN escaping one slot of a batched program must degrade only its own
    study; the TRANSIENT marker routes it into the reliability fallback.
    """
    for s in suggestions:
        for name, value in s.parameters.as_dict().items():
            if isinstance(value, float) and not math.isfinite(value):
                raise BatchSlotError(
                    errors_lib.mark_transient(
                        f"BATCH_SLOT_INVALID: non-finite parameter "
                        f"{name!r}={value!r} in batched suggestion"
                        + (f" for study {study!r}" if study else "")
                    )
                )


class BatchExecutor:
    """Continuous-batching engine over shape-bucket queues.

    Thread model: callers (one servicer thread per study, each already
    holding its study's cache-entry lock) block in :meth:`suggest`; a single
    daemon scheduler thread owns flush decisions and runs the batched
    programs, so device dispatch is naturally serialized. The scheduler
    never takes per-study locks — the submitting thread holds them while it
    waits, which is exactly what makes mutating the designer from the
    scheduler safe.
    """

    def __init__(
        self,
        max_batch_size: int = 8,
        max_wait_ms: float = 4.0,
        pad_partial: bool = True,
        stats: Optional[Any] = None,  # serving.stats.ServingStats
        metrics: Optional[metrics_lib.MetricsRegistry] = None,
        time_fn: Callable[[], float] = time.monotonic,
        speculative_max_wait_ms: float = 250.0,
        mesh: Optional[Any] = None,  # parallel.mesh.MeshConfig
        lanes: Optional[Sequence[LaneSpec]] = None,
        admission: Optional[Any] = None,  # serving.admission.AdmissionController
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got {max_batch_size}")
        self.max_batch_size = max_batch_size
        self.max_wait_secs = max(max_wait_ms, 0.0) / 1000.0
        # Starvation cap for the speculative lane: a speculative-only
        # bucket normally flushes only when no live slot is queued anywhere
        # (the idle window), but a live request that COALESCED onto an
        # in-flight speculative compute is waiting on it, so the hold is
        # bounded — after this long the speculative flush runs regardless.
        self.speculative_max_wait_secs = max(speculative_max_wait_ms, 0.0) / 1000.0
        # The N-lane QoS table, keyed by lane name; unknown lane names on
        # a slot fall back to the live lane's rules.
        lane_table = tuple(lanes) if lanes else default_lanes(
            speculative_max_wait_ms
        )
        self._lanes: Dict[str, LaneSpec] = {l.name: l for l in lane_table}
        self._live_lane = min(self._lanes.values(), key=lambda l: l.priority)
        # Weighted fair share across tenants (serving.admission): with a
        # controller attached, live-lane selection is deficit-round-robin
        # by tenant; None (the default) keeps the seed FIFO bit-identical.
        self._admission = admission
        # DRR state, guarded by _cond: per-tenant deficit credits, the
        # stable round-robin ring + cursor, and weighted served-slot
        # totals (the cross-bucket ordering key).
        self._drr_deficit: Dict[str, float] = {}
        self._drr_ring: List[str] = []
        self._drr_cursor = 0
        self._tenant_served: Dict[str, float] = {}
        self.pad_partial = pad_partial
        self._stats = stats
        self._time = time_fn
        self._cond = threading.Condition()
        self._queues: Dict[BucketKey, List[_Slot]] = {}
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # -- mesh execution plane (parallel.mesh, VIZIER_MESH=1) -----------
        # Placements are built eagerly when the config enables the mesh
        # (this is the only path that enumerates devices); disabled = None
        # and every mesh branch below is dead — the seed executor.
        self._placements: Optional[List[Any]] = None
        self._workers: List[threading.Thread] = []
        self._dispatch_cond = threading.Condition()
        self._dispatch_queues: Dict[int, Deque[Tuple[BucketKey, List[_Slot], str]]] = {}
        self._dispatch_closed = False
        # BucketKey -> placement index, sticky from the first flush (the
        # prewarm walker assigns through the same map, so a prewarmed
        # bucket compiles on the placement that later serves it). Guarded
        # by _dispatch_cond.
        self._bucket_placement: Dict[BucketKey, int] = {}
        # Per-placement flush counts; each entry is written only by its
        # own worker thread (no lock — reads may be momentarily stale).
        self._placement_flushes: Dict[str, int] = {}
        if mesh is not None and getattr(mesh, "enabled", False):
            from vizier_tpu.parallel import mesh as mesh_lib

            self._placements = mesh_lib.build_placements(mesh)
            for placement in self._placements:
                self._dispatch_queues[placement.index] = collections.deque()
                self._placement_flushes[placement.label()] = 0
        self._occupancy = self._flushes = self._queue_wait = None
        if metrics is not None:
            self._occupancy = metrics.histogram(
                "vizier_batch_occupancy",
                help="Real (unpadded) slots per batch flush.",
                buckets=[1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64],
            )
            self._flushes = metrics.counter(
                "vizier_batch_flushes",
                help="Batch flushes by reason (full | timeout | drain).",
            )
            self._queue_wait = metrics.histogram(
                "vizier_batch_queue_wait_seconds",
                help="Time a slot spent queued before its batch flushed.",
            )

    # -- submission ---------------------------------------------------------

    def suggest(
        self,
        designer: Any,
        count: Optional[int] = None,
        *,
        speculative: bool = False,
        lane: Optional[str] = None,
    ) -> List[Any]:
        """Routes one study's suggest through the batching engine.

        Unbatchable paths (no resolvable compute-IR program, seeding
        stage, multi-objective, priors, …) run inline on the caller's
        thread — identical to batching off. ``speculative`` (or an
        explicit ``lane`` name) marks the slot's QoS lane: a deferrable
        lane's bucket never flushes while higher-priority slots are
        queued (see :meth:`_take_due`).
        """
        count = count or 1
        resolved = compute_registry.resolve(designer, count)
        if resolved is None or self._closed:
            return designer.suggest(count)
        program, key = resolved
        tracer = tracing_lib.get_tracer()
        tenant = None
        if self._admission is not None:
            from vizier_tpu.serving import admission as admission_lib

            tenant = admission_lib.current_tenant()
        slot = _Slot(
            designer, program, count, self._time(), tracer.current_span(),
            lane=lane or (LANE_SPECULATIVE if speculative else LANE_LIVE),
            tenant=tenant,
        )
        # Joining a non-empty bucket ⇒ this slot will (very likely) ride a
        # batched flush: run its host-side prepare HERE, on the caller's
        # thread, so it overlaps the in-flight flush's device window instead
        # of serializing on the scheduler. A prepare failure stays inline —
        # naturally isolated to this study. An empty bucket stays
        # unprepared: if nobody joins before the window closes, the
        # scheduler hands it back as a plain sequential suggest
        # (bit-identical to batching off).
        with self._cond:
            will_batch = bool(self._queues.get(key))
        if will_batch:
            try:
                slot.item = program.prepare(designer, count)
            except BaseException:
                self._increment("batch_slot_errors")
                raise
        with self._cond:
            closed = self._closed
            if not closed:
                self._ensure_scheduler()
                self._queues.setdefault(key, []).append(slot)
                self._cond.notify_all()
        if closed:
            return designer.suggest(count)
        slot.event.wait()
        return self._complete(slot)

    def _complete(self, slot: _Slot) -> List[Any]:
        """Runs the scheduler's verdict on the waiting thread."""
        if slot.error is not None:
            raise slot.error
        if slot.action == "batched":
            try:
                suggestions = list(
                    slot.program.finalize(slot.designer, slot.item, slot.output)
                )
                check_finite_suggestions(suggestions)
            except BaseException:
                self._increment("batch_slot_errors")
                raise
            self._increment("batched_suggests")
            return suggestions
        if slot.action == "fallback":
            # The shared device program died (OOM, compile failure, chaos):
            # nobody got the batched result; everybody retries alone on its
            # own thread. This slot's error — if its sequential run also
            # fails — stays its own.
            self._increment("batch_fallbacks")
            tracing_lib.add_current_event("batch_executor.fallback_sequential")
            return list(slot.designer.suggest(slot.count))
        return list(slot.designer.suggest(slot.count))  # "sequential"

    def close(self) -> None:
        """Drains every queue (reason "drain") and stops the scheduler
        (plus, in mesh mode, the per-placement workers — the scheduler
        routes the drain batches to them before signalling shutdown)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=30.0)
        if self._placements is not None:
            # Covers the never-started case; the scheduler already set
            # this on exit after routing its drain batches.
            with self._dispatch_cond:
                self._dispatch_closed = True
                self._dispatch_cond.notify_all()
            for worker in self._workers:
                worker.join(timeout=30.0)

    def pending_counts(self) -> Dict[str, int]:
        with self._cond:
            return {k.label(): len(v) for k, v in self._queues.items() if v}

    # -- mesh introspection -------------------------------------------------

    @property
    def mesh_enabled(self) -> bool:
        return self._placements is not None

    def placements(self) -> List[Any]:
        """The device placements (empty when the mesh plane is off)."""
        return list(self._placements or [])

    def placement_flush_counts(self) -> Dict[str, int]:
        """Flushes executed per placement label (mesh mode only)."""
        return dict(self._placement_flushes)

    def bucket_placements(self) -> Dict[str, List[str]]:
        """Sticky bucket -> placement assignment, label -> placement labels.

        Keyed by bucket *label*, which omits the jit statics — buckets that
        differ only in statics share a label, so the value is the list of
        placements assigned across that label's keys.
        """
        if self._placements is None:
            return {}
        by_index = {p.index: p.label() for p in self._placements}
        out: Dict[str, List[str]] = {}
        with self._dispatch_cond:
            for key, idx in self._bucket_placement.items():
                out.setdefault(key.label(), []).append(by_index[idx])
        return {label: sorted(placements) for label, placements in out.items()}

    def _placement_for(self, key: BucketKey):
        """The placement sticky-assigned to ``key`` (least-loaded on first
        sight, stable forever after — one compiled program per (bucket,
        placement)). Caller must NOT hold ``_dispatch_cond``."""
        assert self._placements is not None
        with self._dispatch_cond:
            index = self._bucket_placement.get(key)
            if index is None:
                load: Dict[int, int] = {p.index: 0 for p in self._placements}
                for assigned in self._bucket_placement.values():
                    load[assigned] += 1
                index = min(load, key=lambda i: (load[i], i))
                self._bucket_placement[key] = index
        return self._placements[index]

    def queue_depth(self) -> Dict[str, int]:
        """Queued slots by lane — the speculative admission gate's view of
        whether live traffic is saturating the flush buckets."""
        out = {name: 0 for name in self._lanes}
        with self._cond:
            for slots in self._queues.values():
                for slot in slots:
                    name = slot.lane if slot.lane in out else self._live_lane.name
                    out[name] += 1
        return out

    def live_pending(self) -> int:
        """Queued LIVE (non-speculative) slots across all buckets."""
        return self.queue_depth()["live"]

    # -- scheduling ---------------------------------------------------------

    def _ensure_scheduler(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._scheduler_loop,
                name="vizier-batch-executor",
                daemon=True,
            )
            self._thread.start()
        if self._placements is not None and not self._workers:
            self._workers = [
                threading.Thread(
                    target=self._worker_loop,
                    args=(placement,),
                    name=f"vizier-mesh-worker-{placement.index}",
                    daemon=True,
                )
                for placement in self._placements
            ]
            for worker in self._workers:
                worker.start()

    def _lane_for(self, slot: _Slot) -> LaneSpec:
        return self._lanes.get(slot.lane, self._live_lane)

    def _bucket_lane(self, slots: List[_Slot]) -> LaneSpec:
        """A bucket's effective lane: the lowest-priority-number lane
        among its slots (a deferrable slot rides a priority flush that is
        forming anyway — the seed's spec-slot-on-live-bucket behavior)."""
        return min(
            (self._lane_for(s) for s in slots), key=lambda l: l.priority
        )

    def _fair_order(self, slots: List[_Slot]) -> List[_Slot]:
        """Deficit-round-robin across tenants, FIFO within a tenant.

        Quantum = the tenant's admission weight. Persistent ring/cursor/
        deficit state (caller holds ``_cond``) makes the rotation fair
        across flushes, not just within one. Starvation bound: a light
        tenant's first queued slot is selected within one DRR round, i.e.
        it can be delayed by at most the sum of the OTHER tenants'
        quanta — a continuously-hot tenant cannot push it further back.
        Single-tenant (or tenantless, admission off) input returns FIFO
        unchanged.
        """
        by_tenant: Dict[str, Deque[_Slot]] = collections.OrderedDict()
        for slot in slots:
            by_tenant.setdefault(slot.tenant or "", collections.deque()).append(
                slot
            )
        if len(by_tenant) <= 1:
            return slots
        for tenant in by_tenant:
            if tenant not in self._drr_ring:
                self._drr_ring.append(tenant)
        weight = self._admission.weight
        out: List[_Slot] = []
        remaining = len(slots)
        ring = self._drr_ring
        while remaining:
            self._drr_cursor %= len(ring)
            tenant = ring[self._drr_cursor]
            self._drr_cursor += 1
            queue = by_tenant.get(tenant)
            if not queue:
                # Classic DRR: an idle tenant banks no credit.
                self._drr_deficit.pop(tenant, None)
                continue
            quantum = max(1.0, float(weight(tenant)))
            credit = self._drr_deficit.get(tenant, 0.0) + quantum
            while credit >= 1.0 and queue:
                out.append(queue.popleft())
                remaining -= 1
                credit -= 1.0
            self._drr_deficit[tenant] = credit if queue else 0.0
        return out

    def _order_due(
        self, due: List[Tuple[BucketKey, List[_Slot], str]]
    ) -> List[Tuple[BucketKey, List[_Slot], str]]:
        """Cross-bucket fairness: stable-sort same-priority due batches by
        their tenants' weighted served-slot totals (least-served first),
        then bill the selection — every flush is billed, even a lone one,
        so the credit stays honest across flush cycles. No-op without an
        admission controller."""
        if self._admission is None:
            return due
        weight = self._admission.weight
        if len(due) > 1:

            def served_key(batch):
                _key, slots, _reason = batch
                return min(
                    self._tenant_served.get(s.tenant or "", 0.0)
                    / max(1.0, float(weight(s.tenant)))
                    for s in slots
                )

            due = sorted(due, key=served_key)
        for _key, slots, _reason in due:
            for slot in slots:
                self._tenant_served[slot.tenant or ""] = (
                    self._tenant_served.get(slot.tenant or "", 0.0) + 1.0
                )
        return due

    def _take_due(self) -> List[Tuple[BucketKey, List[_Slot], str]]:
        """Pops every due (key, slots, reason) batch. Caller holds the lock.

        Lane rules: a bucket whose effective lane is non-deferrable
        flushes on the ordinary full/timeout rules. A deferrable-lane
        bucket defers while any strictly-lower-priority slot is queued
        anywhere (priority traffic owns the device; the idle window is
        its admission), flushing only once the queues are clear of
        priority work — or after the lane's ``starvation_cap_ms``, the
        bounded-starvation escape for priority requests that coalesced
        onto an in-flight deferred compute. Due batches come back in
        lane-priority order; same-priority batches are ordered by the
        weighted fair-share credit when admission is on.
        """
        now = self._time()
        due_by_priority: Dict[int, List[Tuple[BucketKey, List[_Slot], str]]] = {}
        deferred: List[Tuple[BucketKey, List[_Slot], LaneSpec]] = []
        min_queued_priority = min(
            (
                self._lane_for(s).priority
                for slots in self._queues.values()
                for s in slots
            ),
            default=0,
        )
        for key, slots in self._queues.items():
            if not slots:
                continue
            if self._closed:
                due_by_priority.setdefault(0, []).append(
                    (key, slots[:], "drain")
                )
                slots.clear()
                continue
            lane = self._bucket_lane(slots)
            if lane.deferrable and min_queued_priority < lane.priority:
                deferred.append((key, slots, lane))
                continue
            bucket_due = due_by_priority.setdefault(lane.priority, [])
            if len(slots) >= self.max_batch_size:
                ordered = (
                    self._fair_order(slots)
                    if self._admission is not None
                    and not lane.deferrable
                    else slots
                )
                while len(ordered) >= self.max_batch_size:
                    bucket_due.append(
                        (key, ordered[: self.max_batch_size], "full")
                    )
                    del ordered[: self.max_batch_size]
                slots[:] = ordered
            # Oldest by enqueue time, not position: a DRR-reordered
            # remainder is no longer FIFO (identical for FIFO queues).
            if slots and now - min(
                s.enqueued_at for s in slots
            ) >= self.max_wait_secs:
                bucket_due.append((key, slots[:], "timeout"))
                slots.clear()
        for key, slots, lane in deferred:
            if not slots:
                continue
            waited = now - slots[0].enqueued_at
            cap = max(lane.starvation_cap_ms, 0.0) / 1000.0
            if waited >= cap:
                reason = "spec_starved"
            else:
                continue
            # A deferred bucket may have grown past the batch size: flush
            # in max-size chunks so the compiled shape stays the bucket's.
            bucket_due = due_by_priority.setdefault(lane.priority, [])
            while len(slots) > self.max_batch_size:
                bucket_due.append((key, slots[: self.max_batch_size], "full"))
                del slots[: self.max_batch_size]
            bucket_due.append((key, slots[:], reason))
            slots.clear()
        out: List[Tuple[BucketKey, List[_Slot], str]] = []
        for priority in sorted(due_by_priority):
            out.extend(self._order_due(due_by_priority[priority]))
        return out

    def _next_deadline(self) -> Optional[float]:
        """Seconds until the next queued bucket becomes due (lock held)."""
        min_queued_priority = min(
            (
                self._lane_for(s).priority
                for slots in self._queues.values()
                for s in slots
            ),
            default=0,
        )
        deadline = None
        for slots in self._queues.values():
            if not slots:
                continue
            lane = self._bucket_lane(slots)
            if lane.deferrable and min_queued_priority < lane.priority:
                window = max(lane.starvation_cap_ms, 0.0) / 1000.0
            else:
                window = self.max_wait_secs
            due_at = min(s.enqueued_at for s in slots) + window
            if deadline is None or due_at < deadline:
                deadline = due_at
        if deadline is None:
            return None
        return max(deadline - self._time(), 0.0)

    def _scheduler_loop(self) -> None:
        while True:
            with self._cond:
                due = self._take_due()
                if not due:
                    if self._closed:
                        self._signal_workers_closed()
                        return
                    self._cond.wait(timeout=self._next_deadline())
                    continue
            if self._placements is None:
                # Seed path: the scheduler thread executes flushes itself
                # (device dispatch naturally serialized).
                for key, slots, reason in due:
                    self._execute(key, slots, reason)
            else:
                # Mesh path: the scheduler only FORMS flushes; execution
                # fans out to the per-placement workers so different
                # buckets dispatch to different devices concurrently.
                for key, slots, reason in due:
                    placement = self._placement_for(key)
                    with self._dispatch_cond:
                        self._dispatch_queues[placement.index].append(
                            (key, slots, reason)
                        )
                        self._dispatch_cond.notify_all()

    def _signal_workers_closed(self) -> None:
        if self._placements is None:
            return
        with self._dispatch_cond:
            self._dispatch_closed = True
            self._dispatch_cond.notify_all()

    def _worker_loop(self, placement: Any) -> None:
        """One placement's dispatch thread: executes its bucket queue.

        Pops under the dispatch lock, executes outside it — a flush's
        device dispatch never runs under any executor lock (the lock-order
        pass's no-compute-under-lock rule covers these threads too).
        """
        queue = self._dispatch_queues[placement.index]
        while True:
            with self._dispatch_cond:
                while not queue and not self._dispatch_closed:
                    self._dispatch_cond.wait()
                if not queue and self._dispatch_closed:
                    return
                key, slots, reason = queue.popleft()
            self._execute(key, slots, reason, placement)
            self._placement_flushes[placement.label()] += 1

    # -- execution ----------------------------------------------------------

    def _observe_flush(
        self,
        key: BucketKey,
        slots: List[_Slot],
        reason: str,
        placement: Optional[Any] = None,
    ) -> None:
        now = self._time()
        label = key.label()
        # The device label only exists in mesh mode so the seed path's
        # metric series stay byte-identical with the mesh off.
        device = {"device": placement.label()} if placement is not None else {}
        if self._flushes is not None:
            self._flushes.inc(reason=reason, **device)
            self._occupancy.observe(len(slots), bucket=label, **device)
            for slot in slots:
                self._queue_wait.observe(
                    now - slot.enqueued_at, bucket=label, **device
                )
        if self._stats is not None:
            self._stats.increment("batch_flushes")
            if placement is not None:
                self._stats.increment("mesh_flushes")
        recorder = recorder_lib.get_recorder()
        if recorder.enabled:
            # Flush membership for the flight recorder: the member suggests'
            # trace ids tie this fleet-scoped event back to each study's
            # own ring (their request spans carry the same ids).
            recorder.record(
                None,
                "batch_flush",
                bucket=label,
                occupancy=len(slots),
                reason=reason,
                device=placement.label() if placement is not None else None,
                members=[
                    s.span.trace_id for s in slots if s.span is not None
                ],
            )

    def _execute(
        self,
        key: BucketKey,
        slots: List[_Slot],
        reason: str,
        placement: Optional[Any] = None,
    ) -> None:
        self._observe_flush(key, slots, reason, placement)
        tracer = tracing_lib.get_tracer()
        device_attr = (
            {"device": placement.label()} if placement is not None else {}
        )
        with tracer.span(
            "batch_executor.flush",
            bucket=key.label(),
            occupancy=len(slots),
            reason=reason,
            **device_attr,
        ) as span:
            # Link the flush span and every member's request span both ways:
            # a member trace shows WHICH batch served it, the flush span
            # shows WHO shared the dispatch.
            for slot in slots:
                if slot.span is not None and span is not None:
                    span.add_link(slot.span.context(), name="batch_member")
                    slot.span.add_link(span.context(), name="batch_flush")
                    slot.span.set_attribute("batch_occupancy", len(slots))
            if len(slots) == 1 and slots[0].item is None:
                # No batchmates and never prepared: hand back the plain
                # sequential path, bit-identical to batching off (and no
                # vmap overhead). The waiter runs it on its own thread.
                slots[0].action = "sequential"
                slots[0].event.set()
                return
            self._execute_batched(slots, placement)

    def _increment(self, field: str, amount: int = 1) -> None:
        if self._stats is not None and amount:
            self._stats.increment(field, amount)

    def _execute_batched(
        self, slots: List[_Slot], placement: Optional[Any] = None
    ) -> None:
        # Prepare any slot that arrived into an empty bucket (typically the
        # flush's first member; the rest prepared on their own threads at
        # submit time). Slot-isolated: a study whose encode/RNG work raises
        # is dropped from the batch before the device program runs.
        live: List[_Slot] = []
        for slot in slots:
            if slot.item is None:
                try:
                    slot.item = slot.program.prepare(slot.designer, slot.count)
                except BaseException as e:
                    slot.error = e
                    self._increment("batch_slot_errors")
                    slot.event.set()
                    continue
            live.append(slot)
        if not live:
            return
        # A lone prepare survivor still goes through the batched program:
        # its RNG draws already happened in batch order, and pad_partial
        # keeps the compiled shape identical either way.
        program = live[0].program
        # A shardable program on a mesh placement pads at SHARD granularity
        # (DevicePlacement.pad_to — a multiple of the placement's device
        # count, so every device holds an equal slice of the study axis)
        # and receives the placement so it can commit the stacked batch
        # onto the submesh. Anything else keeps the seed padding contract.
        shardable = placement is not None and getattr(
            program, "shardable_batch_axis", ""
        )
        if shardable:
            pad_to = placement.pad_to(len(live), self.max_batch_size)
        else:
            pad_to = self.max_batch_size if self.pad_partial else None
        try:
            # Slot 0's resolved program runs the bucket's device body (the
            # bucket key guarantees every slot resolves the same kind; a
            # chaos-wrapped slot 0 therefore poisons the shared program,
            # exercising the whole-batch fallback — the IR-level twin of
            # the old designer.batch_execute dispatch).
            if shardable:
                outputs = program.device_program(
                    [slot.item for slot in live],
                    pad_to=pad_to,
                    placement=placement,
                )
            else:
                outputs = program.device_program(
                    [slot.item for slot in live], pad_to=pad_to
                )
        except BaseException:
            # The shared device program died: every slot retries alone on
            # its own waiting thread (see _complete), errors slot-isolated.
            tracing_lib.add_current_event(
                "batch_executor.fallback_sequential", slots=len(live)
            )
            for slot in live:
                slot.action = "fallback"
                slot.event.set()
            return
        for slot, output in zip(live, outputs):
            slot.output = output
            slot.action = "batched"
            slot.event.set()

    # -- compile prewarm ----------------------------------------------------

    def prewarm(
        self,
        problem: Any,  # pyvizier ProblemStatement
        designer_factory: Callable[..., Any],
        *,
        max_trials: int = 32,
        counts: Sequence[int] = (1,),
        batch_sizes: Optional[Sequence[int]] = None,
        rng_seed: int = 0,
    ) -> List[dict]:
        """Walks the padding-bucket grid and compiles the batched programs.

        For every ``pad_trials`` bucket covering studies up to ``max_trials``
        and every requested suggestion ``count``, synthetic studies are
        trained + swept once at batch sizes {1, max} (1 warms the sequential
        per-study programs, max the vmapped multi-study programs, which —
        with ``pad_partial`` — is the only batched shape that ever runs).
        In mesh mode the batched sizes are instead the placements'
        shard-granularity padding grid (``DevicePlacement.pad_grid``) and
        each bucket compiles on its sticky-assigned placement — exactly
        the (shape, placement) pairs live flushes will use.
        First-request latency then pays no XLA compile. Returns one report
        row per (bucket, count, batch_size) with wall seconds.
        """
        from vizier_tpu.designers import quasi_random
        from vizier_tpu.pyvizier import trial as trial_

        if batch_sizes:
            sizes = tuple(batch_sizes)
        elif self._placements is not None:
            # Mesh mode: the batched shapes a placement can flush are its
            # shard-granularity padding grid (not just {max}); compile all
            # of them plus the sequential singleton. The per-placement
            # grids are identical when shard counts are equal (the normal
            # case), and de-duped otherwise.
            grid = sorted(
                {
                    size
                    for placement in self._placements
                    for size in placement.pad_grid(self.max_batch_size)
                }
            )
            sizes = tuple([1] + [s for s in grid if s != 1])
        else:
            sizes = (1, self.max_batch_size)
        probe = designer_factory(problem)
        schedule = probe._converter.padding
        report: List[dict] = []
        for bucket in schedule.trial_bucket_grid(max_trials):
            for count in counts:
                for size in sizes:
                    t0 = time.perf_counter()
                    designers = []
                    for j in range(size):
                        d = designer_factory(problem)
                        seeder = quasi_random.QuasiRandomDesigner(
                            problem.search_space, seed=rng_seed + j
                        )
                        trials = []
                        for i, s in enumerate(seeder.suggest(bucket)):
                            t = s.to_trial(i + 1)
                            t.complete(
                                trial_.Measurement(
                                    metrics={
                                        m.name: 0.1 * ((i + j) % 7)
                                        for m in problem.metric_information
                                    }
                                )
                            )
                            trials.append(t)
                        from vizier_tpu.algorithms import core as core_lib

                        d.update(core_lib.CompletedTrials(trials))
                        designers.append(d)
                    status = "ok"
                    try:
                        if size == 1:
                            designers[0].suggest(count)
                        else:
                            # Same calling convention as suggest() above:
                            # registry resolution refreshes per-designer
                            # mode state (e.g. the exact↔sparse surrogate
                            # auto-switch) that prepare snapshots into its
                            # item, and hands back the program whose
                            # device body this bucket compiles.
                            resolved = [
                                compute_registry.resolve(d, count)
                                for d in designers
                            ]
                            if any(r is None for r in resolved):
                                designers[0].suggest(count)
                            else:
                                program, key = resolved[0]
                                items = [
                                    program.prepare(d, count)
                                    for d in designers
                                ]
                                # Compile through the same placement
                                # assignment + shard-granularity padding
                                # live flushes of this bucket will use.
                                placement = (
                                    self._placement_for(key)
                                    if self._placements is not None
                                    and getattr(
                                        program, "shardable_batch_axis", ""
                                    )
                                    else None
                                )
                                if placement is not None:
                                    outputs = program.device_program(
                                        items,
                                        pad_to=placement.pad_to(
                                            size, self.max_batch_size
                                        ),
                                        placement=placement,
                                    )
                                else:
                                    outputs = program.device_program(
                                        items,
                                        pad_to=(
                                            self.max_batch_size
                                            if self.pad_partial
                                            else None
                                        ),
                                    )
                                for d, item, out in zip(
                                    designers, items, outputs
                                ):
                                    program.finalize(d, item, out)
                    except Exception as e:  # prewarm must never block serving
                        status = f"error:{type(e).__name__}"
                    report.append(
                        dict(
                            pad_trials=bucket,
                            count=count,
                            batch_size=size,
                            seconds=round(time.perf_counter() - t0, 4),
                            status=status,
                        )
                    )
        return report
