"""The mesh execution plane: device placements for the batch executor.

The batch executor (``parallel.batch_executor``) fuses N same-bucket
studies into ONE vmapped XLA program — but until this module, every flush
ran on ONE device and a single scheduler thread serialized ALL device
dispatch, so a pod slice served suggestions no faster than one chip. This
module carves the process's devices into **placements** (submeshes) that
the executor schedules over:

- **intra-flush sharding** — a flush dispatched to a placement with S > 1
  devices is sharded over its leading study axis (``NamedSharding`` over
  a 1-D submesh, composing with the per-restart/per-pool sharding in
  ``parallel/__init__``): one fused program spans the placement's devices
  and the padded-slot masking carries over unchanged, just at sharded
  granularity;
- **inter-flush concurrency** — DIFFERENT buckets are sticky-assigned to
  different placements and executed by per-placement worker threads, so
  concurrent buckets no longer serialize through one scheduler thread;
- **shard-granularity padding** — a single-device flush always pads to
  ``max_batch_size`` (one compiled shape per bucket); a mesh placement
  pads to the next power-of-two multiple of its shard count instead
  (``pad_to``), so a placement never computes more padded slots than one
  grid step above its live occupancy. The compiled-shape set per
  (bucket, placement) is the small fixed grid :meth:`pad_grid` — the
  jit-stability contract tests pin it.

Placement assignment is sticky (first flush of a bucket picks the least
loaded placement; every later flush of that bucket reuses it), so each
bucket compiles on exactly one placement — the prewarm walker compiles
through the same assignment path.

Everything here is opt-in: ``VIZIER_MESH=0`` (the default) never touches
``jax.devices()`` and the executor keeps its single-device, bit-identical
seed behavior. The multi-host coordinator seam (:func:`multihost_mesh`)
makes a real pod slice a config change: the same ``VIZIER_MESH*`` switches
plus a coordinator address turn the local device list into the global one.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, List, Optional, Sequence, Tuple

# All VIZIER_* switches are declared in (and read through) the central
# registry; enforced by the env_registry analysis pass.
from vizier_tpu.analysis import registry as _registry


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Knobs for the mesh execution plane (``VIZIER_MESH*``).

    ``enabled=False`` (the default) is the bit-identical single-device
    seed path: no device enumeration, no worker threads, no sharding.
    """

    # Master switch: carve devices into placements and run the executor's
    # per-placement dispatch workers.
    enabled: bool = False
    # Devices to use (0 = every device jax reports). Capped at the
    # process's device count.
    num_devices: int = 0
    # Devices per placement submesh. 1 (the default) gives pure placement
    # concurrency — N single-device placements executing different buckets
    # concurrently. >1 additionally shards each flush's study axis over
    # the placement's devices.
    shard_devices: int = 1
    # Multi-host coordinator seam (``multihost_mesh``): when set, the
    # process joins a jax.distributed cluster before building placements,
    # so a pod slice is config, not code. Empty = single host.
    coordinator_address: str = ""
    num_processes: int = 0
    process_id: int = -1

    @classmethod
    def from_env(cls) -> "MeshConfig":
        return cls(
            enabled=_registry.env_set("VIZIER_MESH"),
            num_devices=_registry.env_int("VIZIER_MESH_DEVICES", 0),
            shard_devices=max(
                1, _registry.env_int("VIZIER_MESH_SHARD_DEVICES", 1)
            ),
            coordinator_address=_registry.env_str("VIZIER_MESH_COORDINATOR"),
            num_processes=_registry.env_int("VIZIER_MESH_PROCESSES", 0),
            process_id=_registry.env_int("VIZIER_MESH_PROCESS_ID", -1),
        )


class DevicePlacement:
    """One schedulable device group: a 1-D submesh plus its padding grid.

    The executor's unit of dispatch — each placement owns one worker
    thread and the buckets sticky-assigned to it. ``shard`` commits a
    stacked flush pytree onto the submesh (leading study axis sharded
    over the devices; with one device this is a plain placement pin), so
    one compiled program exists per (bucket, placement).
    """

    def __init__(self, index: int, devices: Sequence[Any]):
        if not devices:
            raise ValueError("A DevicePlacement needs at least one device.")
        self.index = index
        self.devices = tuple(devices)
        self._sharding = None  # built lazily (needs jax)

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    def label(self) -> str:
        """Low-cardinality metrics/tracing label (one per placement)."""
        return f"mesh{self.index}"

    def describe(self) -> str:
        ids = ",".join(str(getattr(d, "id", d)) for d in self.devices)
        return f"mesh{self.index}[devices {ids}]"

    def batch_sharding(self):
        """``NamedSharding`` over the leading (study) axis of this
        placement's 1-D submesh."""
        if self._sharding is None:
            import numpy as np
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            mesh = Mesh(np.asarray(self.devices), ("batch",))
            self._sharding = NamedSharding(mesh, PartitionSpec("batch"))
        return self._sharding

    def shard(self, tree: Any) -> Any:
        """Commits a stacked (leading-study-axis) pytree onto the submesh.

        Every stacked leaf carries the batch axis first, so one leading-
        axis spec covers the whole tree; the executor guarantees the
        padded batch is a multiple of ``num_devices``.
        """
        import jax

        sharding = self.batch_sharding()
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), tree
        )

    # -- shard-granularity padding -----------------------------------------

    def pad_to(self, occupancy: int, max_batch_size: int) -> int:
        """The padded batch for ``occupancy`` live slots on this placement.

        Next power-of-two multiple of the shard count, capped at the full
        bucket shape (``ceil(max_batch_size / S) * S``): every device gets
        an equal slot count (sharding needs the batch divisible by S) and
        the flush never computes more than one grid step of padding —
        unlike the single-device executor's flat pad-to-max, which makes a
        low-occupancy flush pay for ``max_batch_size`` slots.
        """
        s = self.num_devices
        chunks = max(1, math.ceil(occupancy / s))
        cap = max(chunks, math.ceil(max_batch_size / s))
        q = 1
        while q < chunks:
            q *= 2
        return s * min(q, cap)

    def pad_grid(self, max_batch_size: int) -> List[int]:
        """Every padded batch shape :meth:`pad_to` can produce — the
        compiled-shape grid the prewarm walker compiles per (bucket,
        placement) and the jit-stability tests pin."""
        s = self.num_devices
        cap = max(1, math.ceil(max_batch_size / s))
        grid: List[int] = []
        q = 1
        while q < cap:
            grid.append(s * q)
            q *= 2
        grid.append(s * cap)
        return grid


def multihost_mesh(config: Optional[MeshConfig] = None):
    """The multi-host coordinator seam: the device list a pod slice serves
    flushes over.

    Single host (no coordinator configured): the local device list. With
    ``coordinator_address`` set (``VIZIER_MESH_COORDINATOR``), the process
    joins the jax.distributed cluster first — the same explicit-coordinator
    wiring ``parallel.initialize_multihost`` uses — and the returned list
    spans every host's devices, so the executor's placements tile the whole
    pod slice. Placement workers dispatch only buckets assigned to
    placements containing local devices; remote-spanning placements shard
    their flushes over DCN exactly like the test-proven global-mesh data
    plane in ``tests/parallel/test_multihost_explicit.py``.
    """
    import jax

    config = config or MeshConfig.from_env()
    if config.coordinator_address:
        from vizier_tpu import parallel as parallel_lib

        parallel_lib.initialize_multihost(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes or None,
            process_id=(
                config.process_id if config.process_id >= 0 else None
            ),
        )
    return list(jax.devices())


def _carve_device_groups(devices: Sequence[Any], s: int) -> List[List[Any]]:
    """Groups ``devices`` into shard groups of ``s``, process-local first.

    On a multi-host mesh a naive flat slice can put one submesh's devices
    on different hosts, turning every intra-flush all-gather into a DCN
    hop. Instead: group devices by ``process_index`` (order preserved),
    carve each process's devices into s-sized groups, then pool each
    process's remainder — in process order — into cross-process groups so
    no device is dropped that a flat slice would have used. A final
    remainder smaller than ``s`` is dropped, exactly like before.
    """
    by_process: dict = {}
    order: List[Any] = []
    for device in devices:
        pid = getattr(device, "process_index", 0)
        if pid not in by_process:
            by_process[pid] = []
            order.append(pid)
        by_process[pid].append(device)
    groups: List[List[Any]] = []
    leftovers: List[Any] = []
    for pid in order:
        local = by_process[pid]
        for start in range(0, len(local) - s + 1, s):
            groups.append(local[start : start + s])
        leftovers.extend(local[len(local) - len(local) % s :])
    for start in range(0, len(leftovers) - s + 1, s):
        groups.append(leftovers[start : start + s])
    return groups


def build_placements(config: MeshConfig) -> List[DevicePlacement]:
    """Carves the (possibly multi-host) device list into placements.

    ``num_devices`` caps how many devices participate; ``shard_devices``
    groups them into equal submeshes, **preferring process-local groups**
    on multi-host meshes (see :func:`_carve_device_groups`) so a
    placement's intra-flush sharding stays on-host whenever the counts
    allow. A trailing remainder group smaller than ``shard_devices`` is
    dropped rather than compiled as its own odd shape — use divisible
    counts for full utilization.
    """
    devices = multihost_mesh(config)
    if config.num_devices:
        devices = devices[: config.num_devices]
    s = max(1, config.shard_devices)
    groups = _carve_device_groups(devices, s)
    placements = [
        DevicePlacement(i, group) for i, group in enumerate(groups)
    ]
    if not placements:  # fewer devices than one shard group: use them all
        placements = [DevicePlacement(0, list(devices))]
    return placements
