"""Regret suite: the BASELINE.md eval configs, one JSON report.

Runs the flagship designers on the driver-specified configurations (Branin,
mixed space, 20-D BBOB eagle, multi-objective ZDT) and writes
``regret_report.json`` with best-so-far numbers — the measurement instrument
for regret parity (the reference publishes no tables; BASELINE.md directs
measuring behaviorally).

Usage: ``python regret_suite.py [--scale 0.25] [--out regret_report.json]``
(scale shrinks budgets for CPU smoke runs).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _run(designer_factory, experimenter, num_trials, batch, seed=0):
    from vizier_tpu import benchmarks

    state = benchmarks.BenchmarkState.from_designer_factory(
        experimenter, designer_factory, seed=seed
    )
    benchmarks.BenchmarkRunner(
        [benchmarks.GenerateAndEvaluate(batch)], num_repeats=max(num_trials // batch, 1)
    ).run(state)
    return state


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--scale", type=float, default=1.0)
    parser.add_argument("--out", default="regret_report.json")
    parser.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu"],
        help="Pin the JAX platform (use 'cpu' for smoke runs on machines "
        "whose ambient TPU plugin would otherwise be picked up).",
    )
    args = parser.parse_args()
    s = args.scale
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from vizier_tpu import benchmarks
    from vizier_tpu import pyvizier as vz
    from vizier_tpu.benchmarks.experimenters.synthetic import bbob, multiobjective
    from vizier_tpu.benchmarks.analyzers import convergence_curve as cc
    from vizier_tpu.designers import RandomDesigner
    from vizier_tpu.designers.eagle_strategy import EagleStrategyDesigner
    from vizier_tpu.designers.evolution import NSGA2Designer
    from vizier_tpu.designers.gp_bandit import VizierGPBandit
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
    from vizier_tpu.pyvizier import trial as trial_lib

    report = {}
    t_start = time.time()

    def gp(problem, seed=None, **kw):
        return VizierGPBandit(
            problem,
            rng_seed=seed or 0,
            max_acquisition_evaluations=max(int(10_000 * s), 1000),
            num_seed_trials=5,
        )

    def ucbpe(problem, seed=None, **kw):
        return VizierGPUCBPEBandit(
            problem,
            rng_seed=seed or 0,
            max_acquisition_evaluations=max(int(5_000 * s), 500),
            num_seed_trials=5,
        )

    # -- Config 1: GP-UCB on Branin (2-D classic) --------------------------
    def branin_best(factory, seed):
        exp = benchmarks.NumpyExperimenter(
            bbob.Branin, benchmarks.bbob_problem(2, metric_name="bbob_eval")
        )
        state = _run(factory, exp, num_trials=max(int(32 * s), 12), batch=2, seed=seed)
        trials = state.algorithm.supporter.GetTrials(
            status_matches=vz.TrialStatus.COMPLETED
        )
        return min(t.final_measurement.metrics["bbob_eval"].value for t in trials)

    report["branin_gp_ucb"] = {
        "best": [branin_best(gp, seed) for seed in (1, 2)],
        "optimum": 0.397887,
        "baseline_random": [branin_best(
            lambda p, **kw: RandomDesigner(p.search_space, seed=kw.get("seed", 0)), seed
        ) for seed in (1, 2)],
    }

    # -- Config 2: DEFAULT on the README mixed space -----------------------
    def mixed_best(factory, seed):
        problem = vz.ProblemStatement()
        root = problem.search_space.root
        root.add_float_param("lr", 1e-4, 1e-1, scale_type=vz.ScaleType.LOG)
        root.add_int_param("layers", 1, 8)
        root.add_categorical_param("opt", ["adam", "sgd", "rmsprop"])
        problem.metric_information.append(
            vz.MetricInformation(name="acc", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )

        class MixedExp(benchmarks.Experimenter):
            def evaluate(self, suggestions):
                for t in suggestions:
                    lr = t.parameters.get_value("lr")
                    layers = t.parameters.get_value("layers")
                    opt = t.parameters.get_value("opt")
                    acc = (
                        1.0
                        - (np.log10(lr) + 2.0) ** 2 * 0.2
                        - 0.03 * abs(layers - 4)
                        + (0.05 if opt == "adam" else 0.0)
                    )
                    t.complete(trial_lib.Measurement(metrics={"acc": acc}))

            def problem_statement(self):
                return problem

        state = _run(factory, MixedExp(), num_trials=max(int(30 * s), 12), batch=3, seed=seed)
        trials = state.algorithm.supporter.GetTrials(
            status_matches=vz.TrialStatus.COMPLETED
        )
        return max(t.final_measurement.metrics["acc"].value for t in trials)

    report["mixed_default_ucbpe"] = {
        "best": [mixed_best(ucbpe, 1)],
        "optimum": 1.05,
    }

    # -- Config 3: Eagle on 20-D BBOB (Rastrigin, Sphere) ------------------
    eagle_results = {}
    for fn_name in ("Sphere", "Rastrigin"):
        exp = benchmarks.NumpyExperimenter(
            bbob.BBOB_FUNCTIONS[fn_name], benchmarks.bbob_problem(20)
        )
        state = _run(
            lambda p, **kw: EagleStrategyDesigner(p, seed=kw.get("seed", 0)),
            exp,
            num_trials=max(int(200 * s), 50),
            batch=10,
        )
        trials = state.algorithm.supporter.GetTrials(
            status_matches=vz.TrialStatus.COMPLETED
        )
        eagle_results[fn_name] = min(
            t.final_measurement.metrics["bbob_eval"].value for t in trials
        )
    report["eagle_20d_bbob"] = eagle_results

    # -- Config 4: multi-objective on ZDT1 (NSGA2 + GP HV-scalarized) ------
    mo_results = {}
    for name, factory in (
        ("nsga2", lambda p, **kw: NSGA2Designer(p, population_size=20, seed=0)),
        ("gp_hv_ucb", gp),
    ):
        exp = multiobjective.MultiObjectiveExperimenter.zdt("zdt1", dimension=6)
        state = _run(factory, exp, num_trials=max(int(60 * s), 20), batch=5)
        trials = state.algorithm.supporter.GetTrials(
            status_matches=vz.TrialStatus.COMPLETED
        )
        curve = cc.HypervolumeCurveConverter(
            list(exp.problem_statement().metric_information),
            reference_point=np.array([-1.1, -6.0], dtype=np.float32),
        ).convert(trials)
        mo_results[name] = float(curve.ys[0, -1])
    report["zdt1_hypervolume"] = mo_results

    report["elapsed_secs"] = round(time.time() - t_start, 1)
    report["scale"] = s
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
